"""Node agent: the per-node daemon that executes scheduled work.

The reference never had to write this loop — Azure Batch's hosted agent
did task pickup, retries, and exit-code plumbing (SURVEY.md section 7
'hard parts'). Ours is storage-mediated like everything else: tasks
arrive on a per-pool queue, assignment is won by optimistic-concurrency
claims on task entities, gang (multi-instance) tasks rendezvous through
a gang table, and results flow back through tables + object uploads.

Lifecycle of a node (entity in TABLE_NODES):
    creating -> starting (node prep) -> idle <-> running -> offline
                 \\-> start_task_failed            \\-> unusable

Lifecycle of a task (entity in TABLE_TASKS):
    pending -> assigned -> running -> completed | failed
         \\-> blocked (dependency permanently unsatisfiable)

The agent runs identically under the fake substrate (thread per node),
the localhost substrate (process), and on a real TPU VM worker
(systemd unit installed by nodeprep).
"""

from __future__ import annotations

import collections
import contextlib
import dataclasses
import json
import os
import subprocess
import threading
import time
import uuid
from typing import Callable, Optional

from batch_shipyard_tpu.agent import preemption as preempt_mod
from batch_shipyard_tpu.agent import progress as progress_mod
from batch_shipyard_tpu.agent import task_runner
from batch_shipyard_tpu.compilecache import manager as cc_manager
from batch_shipyard_tpu.compilecache import seeding as cc_seeding
from batch_shipyard_tpu.config.settings import (
    JaxDistributedSettings, MultiInstanceSettings, PoolSettings)
from batch_shipyard_tpu.goodput import events as goodput_events
from batch_shipyard_tpu.jobs import launcher
from batch_shipyard_tpu.sched import policy as sched_policy
from batch_shipyard_tpu.state import leases as state_leases
from batch_shipyard_tpu.state import names
from batch_shipyard_tpu.state import resilient as state_resilient
from batch_shipyard_tpu.state.base import (
    EntityExistsError, EtagMismatchError, NotFoundError, StateStore)
from batch_shipyard_tpu.trace import context as trace_context
from batch_shipyard_tpu.trace import profiling as trace_profiling
from batch_shipyard_tpu.trace import spans as trace_spans
from batch_shipyard_tpu.utils import util

logger = util.get_logger(__name__)

_OUTPUT_STREAM_CHUNK = 4 * 1024 * 1024

# How often a worker slot re-reads the pool's task-queue shard count
# to pick up grow-only autoscale (jobs/manager.py). Slow on purpose:
# a stale scan only under-uses new fan-out, it never loses messages.
_SHARD_REFRESH_SECONDS = 20.0

# Identity file worker 0 writes inside a shared scratch dir; other
# workers read it THROUGH the published path to decide whether they
# already share the host's filesystem.
_SCRATCH_NONCE = ".shipyard_scratch_nonce"


class TaskEnvError(Exception):
    """Task environment synthesis failed (unresolvable secret,
    malformed env block): the task must FAIL with the reason — an
    exception escaping to the worker loop would bounce its queue
    message forever."""


class NodeUnusableError(Exception):
    """Raised by a nodeprep callable to mark the node unusable (as
    opposed to start-task-failed): the node finished booting but cannot
    serve tasks — triggers attempt_recovery_on_unusable handling."""


class _AdoptedProc:
    """Handle for a process this agent did not spawn (crash-restart
    adoption): exposes the ``pid`` every _live_procs consumer —
    term_task, eviction enforcement, zap, the chaos injectors —
    actually uses. There is no Popen to wait() on; the adoption
    watcher polls liveness and reads the exit-code sentinel."""

    def __init__(self, pid: Optional[int]) -> None:
        self.pid = pid or -1


@dataclasses.dataclass
class NodeIdentity:
    pool_id: str
    node_id: str
    node_index: int
    hostname: str
    internal_ip: str
    slice_index: int = 0
    worker_index: int = 0


class NodeAgent:
    def __init__(self, store: StateStore, identity: NodeIdentity,
                 pool: PoolSettings, work_dir: str,
                 heartbeat_interval: float = 5.0,
                 poll_interval: float = 0.2,
                 gang_timeout: float = 600.0,
                 node_stale_seconds: float = 30.0,
                 job_state_ttl: float = 5.0,
                 nodeprep: Optional[Callable[["NodeAgent"], None]] = None,
                 image_provisioner: Optional[
                     Callable[["NodeAgent", list[str]], None]] = None,
                 output_upload_cap_bytes: Optional[int] = None,
                 substrate: Optional[object] = None,
                 scratch_mount_runner: Optional[
                     Callable[[str, str], int]] = None,
                 scratch_export_runner: Optional[
                     Callable[[str], int]] = None,
                 scratch_unexport_runner: Optional[
                     Callable[[str], int]] = None,
                 scratch_umount_runner: Optional[
                     Callable[[str], int]] = None,
                 force_remote_scratch: bool = False,
                 scratch_finalize_timeout: float = 120.0,
                 retry_backoff_base: float = 2.0,
                 retry_backoff_cap: float = 300.0,
                 health_quarantine_threshold: float = 0.25,
                 health_probation_seconds: float = 300.0,
                 claim_visibility_seconds: float = 60.0,
                 gang_sweep_interval: float = 60.0,
                 preempt_sweep_interval: float = 30.0,
                 preempt_grace_seconds: float = 20.0,
                 leader_lease_seconds: Optional[float] = None,
                 resilience: Optional[dict] = None,
                 ) -> None:
        # Store-outage ride-through (state/resilient.py): when
        # configured, every store op this agent issues goes through
        # the resilient wrapper — critical ops retry through outages,
        # advisory ops (goodput/trace/heartbeat) ride the per-node
        # local WAL and replay in order on recovery. The real agent
        # process (agent/__main__.py) enables it by default; tests
        # and drills opt in via the kwarg so seeded fault schedules
        # keep their historical semantics.
        if resilience is not None:
            store = state_resilient.ResilientStore(
                store,
                journal_path=os.path.join(work_dir,
                                          "store_wal.jsonl"),
                pool_id=identity.pool_id,
                node_id=identity.node_id,
                stop_check=lambda: self.stop_event.is_set(),
                **resilience)
        self.store = store
        self.identity = identity
        self.pool = pool
        self.work_dir = work_dir
        self.heartbeat_interval = heartbeat_interval
        self.poll_interval = poll_interval
        self.gang_timeout = gang_timeout
        self.node_stale_seconds = node_stale_seconds
        self._nodeprep = nodeprep
        self._image_provisioner = image_provisioner
        # Substrate handle for pool-resident services that act on the
        # pool (autoscale resize); None disables those services.
        self._substrate = substrate
        # None = upload task outputs in full (streamed). A configured
        # cap keeps head+tail around an explicit truncation marker.
        self.output_upload_cap_bytes = output_upload_cap_bytes
        # Shared-scratch plumbing commands, injectable so mount/export
        # synthesis and their failure modes run under fault injection
        # (on real pools these shell out to mount/umount/exportfs).
        self._scratch_mount = scratch_mount_runner or self._nfs_mount
        self._scratch_export = (scratch_export_runner or
                                self._nfs_export)
        self._scratch_unexport = (scratch_unexport_runner or
                                  self._nfs_unexport)
        self._scratch_umount = (scratch_umount_runner or
                                self._nfs_umount)
        self._force_remote_scratch = force_remote_scratch
        self._scratch_finalize_timeout = scratch_finalize_timeout
        self.stop_event = threading.Event()
        self._threads: list[threading.Thread] = []
        self._running_tasks = 0
        self._running_lock = threading.Lock()
        # Resolved shared-scratch paths per job (auto_scratch: shared).
        self._shared_scratch: dict[str, str] = {}
        # Short-TTL job cache ((state, profile_request, at)): the
        # disabled/terminated check runs on every queue poll and must
        # not cost a store round trip each time on cloud backends;
        # the profile-request forwarding rides the same read.
        self._job_state_cache: dict[str, tuple] = {}
        self._job_state_ttl = job_state_ttl
        # (job_id, task_id) -> live Popen, for task termination relay.
        self._live_procs: dict[tuple[str, str], object] = {}
        # (job_id, task_id) -> last gang-health probe (rate limiting
        # the claim-failure bounce path).
        self._gang_probe_at: dict[tuple[str, str], float] = {}
        # (gang_pk, instance) claims held LIVE by a worker slot of
        # this process. A claim whose slot crashed (store fault after
        # _gang_claim) leaves joined rows owned by a live node that
        # nothing is running — no observer ever judges them stale, so
        # the gang would wedge forever. Redelivery resumes such a
        # claim, but only when no slot here still holds it (a
        # duplicate message copy must not double-run the instance).
        self._active_gang_claims: set[tuple[str, int]] = set()
        # Orphaned-gang-row janitor cadence (heartbeat loop).
        self.gang_sweep_interval = gang_sweep_interval
        self._last_gang_sweep = time.monotonic()
        # Cooperative-preemption sweep cadence (heartbeat loop,
        # leader-gated like the gang janitor: one unpartitioned task
        # scan per pool per interval). grace = how long a pending
        # higher-priority task must have waited before lower-priority
        # running work is evicted for it (<=0 disables the sweep).
        self.preempt_sweep_interval = preempt_sweep_interval
        self.preempt_grace_seconds = preempt_grace_seconds
        self._last_preempt_sweep = time.monotonic()
        # Lease-based sweep leadership (state/leases.py): one named
        # lease per leader-gated loop, acquired at the loop's own
        # cadence and renewed every heartbeat; the term's fencing
        # epoch is stamped into every sweep write. Default duration
        # scales with the heartbeat so failover latency tracks the
        # deployment's clock (drills with 0.2s beats fail over in
        # ~2s; production's 5s beats in ~20s).
        self.leader_lease_seconds = (
            leader_lease_seconds
            if leader_lease_seconds is not None
            else max(2.0, 4.0 * heartbeat_interval))
        self._sweep_leases: dict[str, state_leases.LeaderLease] = {}
        # Claim batching: a worker poll takes up to slot-count
        # messages (capped) under one visibility window and parks the
        # surplus on this node-local deque; every slot drains the
        # deque before touching the store again, so a busy node pays
        # ~1 queue round trip per k tasks instead of per task.
        self._claim_prefetch: collections.deque = collections.deque()
        self._claim_prefetch_lock = threading.Lock()
        # Server-side task-factory expansion (jobs/expansion.py):
        # the ROLE_EXPANDER leader materializes parked generator
        # specs on a dedicated thread; the heartbeat sweep only
        # checks for work and spawns it (lint forbids slow sweeps).
        self._expander_thread: Optional[threading.Thread] = None
        self._last_expansion_sweep = 0.0
        self.expansion_sweep_interval = max(2.0, heartbeat_interval)
        # Chaos seam (leader_partition): while wall-clock < this, NO
        # lease traffic reaches the store — the leader is partitioned
        # from it, and its authority decays on the local clock alone.
        self.lease_blackout_until = 0.0
        # Chaos seam (agent_restart, fakepod crash_agent_hard):
        # threads cannot be killed, so a simulated agent-process
        # death sets this flag — in-flight completion paths cut off
        # before their first post-exit store write, exactly like the
        # real process dying mid-task. The REVIVED agent's adoption
        # path owns the task from there.
        self._abandoned = False
        # Crash-restart adoption: slots whose previous-process task
        # is still running under an adoption watcher — the worker
        # slot waits its turn instead of oversubscribing the node.
        self._adopted_slots: set[int] = set()
        # Predecessor's last heartbeat (captured by start() before
        # the first upsert overwrites it): the adoption leg's start.
        self._pre_restart_heartbeat: Optional[float] = None
        # (path, requested_at) preempt requests already delivered —
        # same dedup protocol as _profile_delivered (one drain per
        # request; disk markers persist the dedup across restarts).
        self._preempt_delivered: set[tuple] = set()
        # First-seen clock per stale-epoch preempt stamp being held
        # for confirmation before delivery (consumer-side fence for
        # the author-retraction race; _confirm_stale_epoch_request),
        # plus the TTL-cached observer view of the sweep lease term.
        self._preempt_forward_hold: dict[tuple, float] = {}
        self._preempt_leader_cache: Optional[tuple] = None
        # (job_id, task_id) keys THIS agent hard-killed through the
        # eviction escalation: the completion path classifies the
        # exit as evicted (claimable, full budget, neutral health)
        # instead of a wedge/failure. Popped at classification.
        self._evicted_locally: set[tuple[str, str]] = set()
        # Stale preempt-request file janitor cadence (heartbeat
        # loop, per-node disk sweep — shares the gang janitor's
        # interval knob but needs no leader gate: each node owns its
        # own task dirs).
        self._last_preempt_file_sweep = time.monotonic()
        # Short-TTL per-task preempt_request cache ((request, at)):
        # the heartbeat forwarding loop must not cost one store read
        # per live task per beat on cloud backends just to learn no
        # preemption is pending (the _job_state_cache rule). TTL
        # shares _job_state_ttl.
        self._task_preempt_cache: dict[tuple, tuple] = {}
        # (job_id, secret_id) -> resolved env block: one provider
        # round trip per job per node, not per task launch.
        self._env_block_cache: dict[tuple[str, str], dict] = {}
        # Pool image-manifest cache for the strict
        # allow_run_on_missing_image gate: (expires_at, image set)
        # per runtime kind — the hot launch path must not query the
        # whole images table per task.
        self._image_manifest_cache: dict[str, tuple[float, set]] = {}
        # Goodput accounting: wall-clock instant this node last went
        # idle (no claimed/running work); the idle interval is emitted
        # when work next starts. None while work is in flight.
        # _goodput_busy_slots holds slots with a CLAIMED task
        # (claim -> finish/abandon) so a slot mid-prep blocks idle
        # re-arm even before its _running_tasks increment; slot-keyed
        # so an exception path can release idempotently without ever
        # stealing another slot's unit. Both under _running_lock.
        self._goodput_idle_since: Optional[float] = None
        self._goodput_busy_slots: set[int] = set()
        # Pool-wide compile-cache seeding (compilecache/seeding.py):
        # remember the latest.json generation last seeded so the
        # pre-task seed check costs one metadata read, not a download,
        # when nothing changed. Exports run on a background thread
        # (one at a time) so a multi-GB cache upload never sits on
        # the task-completion path.
        self._compile_cache_seen_gen: Optional[int] = None
        self._compile_cache_export_thread: Optional[
            threading.Thread] = None
        # Retry supervisor: exponential backoff parameters for
        # requeued failures (delay = base * 2^retries, capped, with
        # deterministic per-(task, attempt) jitter).
        self.retry_backoff_base = retry_backoff_base
        self.retry_backoff_cap = retry_backoff_cap
        # Claimed-message invisibility window: also the recovery-
        # latency floor after a node crash (the dead node's claim
        # redelivers only when this lapses). Chaos drills shrink it.
        self.claim_visibility_seconds = claim_visibility_seconds
        # Node health score in [0, 1]: decayed by task failures
        # (harder by wedges), recovered by successes. Below the
        # threshold the node quarantines itself — auto-drain: running
        # work finishes, no new claims — and publishes the
        # health/quarantined columns on its node entity so observers
        # (gang recovery, heimdall) exclude it too.
        self._health = 1.0
        self._health_quarantine_threshold = health_quarantine_threshold
        self._node_quarantined = False
        # Quarantine is probational, never permanent: a quarantined
        # node claims nothing, so it can never earn the successes that
        # restore its score — without a timer, a poison job of
        # ordinary buggy tasks (exit 1) would drain EVERY node in the
        # pool forever. After this window the score resets to the
        # threshold: one more failure re-quarantines immediately, one
        # success starts real recovery.
        self._health_probation_seconds = health_probation_seconds
        self._quarantined_at = 0.0
        self._health_lock = threading.Lock()
        # Shared scheduling policy (sched/policy.py): knobs derived
        # once from pool settings; claim_scoring opts the claim path
        # into warm-cache affinity deferral. The preemption sweep's
        # goodput-cost victim ordering and the health/backoff debits
        # are always on — with no hints/failures they price to 0.0
        # and reduce to the historical (priority, task_id) order.
        self._policy_knobs = sched_policy.knobs_from_settings(
            getattr(pool, "sched_policy", None))
        self._claim_scoring = bool(
            getattr(getattr(pool, "sched_policy", None),
                    "claim_scoring", False))
        # Recent task-failure count for the claim-scoring backoff
        # debit: bumped on failure/wedge, drained by successes.
        self._recent_failures = 0
        # Last synced sched-hints JSON per live task, so the
        # heartbeat mirror writes the row only on change.
        self._sched_hints_sent: dict[tuple[str, str], str] = {}
        # Chaos injection seam: heartbeats are suppressed while
        # wall-clock < this (simulated network partition).
        self.heartbeat_blackout_until = 0.0
        # On-demand profiling: (request-file path, requested_at)
        # pairs this agent already delivered — keyed per TARGET FILE
        # so every gang instance dir on a multi-slot node gets its
        # copy, yet no file is ever re-dropped after the harness
        # consumed it (one store flag, one capture per instance).
        self._profile_delivered: set[tuple] = set()
        # Retention sweeps: (monotonic deadline, task dir) for
        # completed tasks whose spec sets retention_time_seconds —
        # the Azure Batch task-constraint retention_time analog
        # (reference batch.py:4859): working files stay on the node
        # for the window, then a heartbeat-loop sweep removes them.
        self._retention: list[tuple[float, str]] = []
        self._retention_lock = threading.Lock()

    # ------------------------- node lifecycle --------------------------

    @property
    def _nid(self) -> tuple[str, str]:
        return self.identity.pool_id, self.identity.node_id

    def _set_node_state(self, state: str, **extra) -> None:
        pool_id, node_id = self._nid
        entity = {
            "state": state,
            "hostname": self.identity.hostname,
            "internal_ip": self.identity.internal_ip,
            "node_index": self.identity.node_index,
            "slice_index": self.identity.slice_index,
            "worker_index": self.identity.worker_index,
            "heartbeat_at": time.time(),
            "task_slots": self.pool.task_slots_per_node,
            names.NODE_COL_HEALTH: self._health,
            names.NODE_COL_QUARANTINED: self._node_quarantined,
        }
        entity.update(extra)
        self.store.upsert_entity(names.TABLE_NODES, pool_id, node_id, entity)

    def _heartbeat(self, **extra) -> None:
        # Chaos seam (chaos/injectors.py heartbeat_blackout): a
        # suppressed heartbeat simulates a partitioned-but-running
        # node without touching the network stack.
        if time.time() < self.heartbeat_blackout_until:
            return
        pool_id, node_id = self._nid
        # Health/quarantine ride on every heartbeat so a one-shot
        # publish lost to a blackout window or store hiccup
        # self-repairs on the next periodic write.
        with self._health_lock:
            health_cols = {
                names.NODE_COL_HEALTH: self._health,
                names.NODE_COL_QUARANTINED: self._node_quarantined,
            }
        # Resilient-store WAL backlog rides every heartbeat so
        # heimdall exports shipyard_journal_backlog_entries per node
        # (0 when the wrapper is off or the journal is drained).
        backlog_fn = getattr(self.store, "journal_backlog", None)
        if callable(backlog_fn):
            health_cols[names.NODE_COL_JOURNAL_BACKLOG] = backlog_fn()
        try:
            self.store.merge_entity(
                names.TABLE_NODES, pool_id, node_id,
                {"heartbeat_at": time.time(),
                 "running_tasks": self._running_tasks,
                 **health_cols, **extra})
        except NotFoundError:
            pass

    def start(self) -> None:
        """Run node prep, then start worker + heartbeat threads."""
        # Crash-restart adoption needs the PREDECESSOR's last
        # heartbeat (the adoption leg's start) BEFORE the first state
        # upsert below overwrites it. One read, only when a previous
        # process left slot ledgers behind.
        slots_dir = os.path.join(self.work_dir, "slots")
        if os.path.isdir(slots_dir) and os.listdir(slots_dir):
            try:
                # Bounded: a restart DURING a store outage must not
                # park the boot thread in the resilient wrapper's
                # 900s retry loop before adoption or any worker slot
                # exists — fail fast into the degrade path instead.
                with self._store_bounded(
                        max(10.0, 2.0 * self.heartbeat_interval)):
                    row = self.store.get_entity(names.TABLE_NODES,
                                                *self._nid)
                self._pre_restart_heartbeat = \
                    float(row.get("heartbeat_at") or 0) or None
            except Exception:  # noqa: BLE001 - adoption degrades
                logger.debug("pre-restart heartbeat probe failed",
                             exc_info=True)
        self._set_node_state("starting")
        marker = os.path.join(self.work_dir, ".nodeprep_finished")
        prep_started = time.time()
        try:
            os.makedirs(self.work_dir, exist_ok=True)
            # Idempotency marker: reboot-resume fast path (reference:
            # $nodeprepfinished, shipyard_nodeprep.sh:1935-1970).
            if not os.path.exists(marker):
                if self._nodeprep is not None:
                    self._nodeprep(self)
                with open(marker, "w", encoding="utf-8") as fh:
                    fh.write(util.datetime_utcnow_iso())
                goodput_events.emit(
                    self.store, self.identity.pool_id,
                    goodput_events.NODE_PREP,
                    node_id=self.identity.node_id,
                    start=prep_started, end=time.time())
        except NodeUnusableError as exc:
            logger.warning("node %s unusable: %s",
                           self.identity.node_id, exc)
            self._set_node_state("unusable", error=str(exc))
            return
        except Exception as exc:
            logger.exception("node prep failed on %s", self.identity.node_id)
            self._set_node_state("start_task_failed", error=str(exc))
            return
        self._set_node_state("idle")
        self._goodput_idle_since = time.time()
        self._rescan_retention_markers()
        # Re-adopt the previous process's still-running work BEFORE
        # the worker slots start polling: the live-proc registry must
        # already name the adopted tasks when the first redelivered
        # message asks whether they are orphans.
        self._adopt_restart_state()
        for slot in range(self.pool.task_slots_per_node):
            thread = threading.Thread(
                target=self._worker_loop, args=(slot,),
                name=f"agent-{self.identity.node_id}-s{slot}", daemon=True)
            thread.start()
            self._threads.append(thread)
        hb = threading.Thread(target=self._heartbeat_loop,
                              name=f"hb-{self.identity.node_id}",
                              daemon=True)
        hb.start()
        self._threads.append(hb)
        # Control messages get their own thread: worker slots block
        # while running tasks, and controls (task termination,
        # shutdown) must still be honored.
        ctrl = threading.Thread(target=self._control_loop,
                                name=f"ctrl-{self.identity.node_id}",
                                daemon=True)
        ctrl.start()
        self._threads.append(ctrl)
        self._start_pool_services()

    def _start_pool_services(self) -> None:
        """Pool-resident daemons on worker 0 (reference: the recurrent
        job manager runs as a job-manager task ON the pool,
        cargo/recurrent_job_manager.py:187 — schedules keep firing
        with no operator terminal alive). Gated by
        pool_specification.pool_services."""
        services = getattr(self.pool, "pool_services", None)
        if services is None or self.identity.node_index != 0:
            return
        if services.schedules:
            from batch_shipyard_tpu.jobs import schedules
            thread = threading.Thread(
                target=schedules.run_pool_schedule_service,
                args=(self.store, self.pool),
                kwargs={"stop_event": self.stop_event,
                        "poll_interval":
                            services.poll_interval_seconds},
                name=f"svc-sched-{self.identity.node_id}", daemon=True)
            thread.start()
            self._threads.append(thread)
            logger.info("pool schedule service running on %s",
                        self.identity.node_id)
        if services.autoscale:
            if self._substrate is None:
                logger.warning(
                    "pool_services.autoscale enabled but this agent "
                    "has no substrate handle; service not started")
                return
            from batch_shipyard_tpu.pool import autoscale as as_mod
            thread = threading.Thread(
                target=as_mod.run_daemon,
                args=(self.store, self._substrate, self.pool),
                kwargs={"stop_event": self.stop_event,
                        "interval": services.poll_interval_seconds},
                name=f"svc-as-{self.identity.node_id}", daemon=True)
            thread.start()
            self._threads.append(thread)
            logger.info("pool autoscale service running on %s",
                        self.identity.node_id)

    def stop(self) -> None:
        self.stop_event.set()

    def join(self, timeout: Optional[float] = None) -> None:
        deadline = None if timeout is None else time.monotonic() + timeout
        for thread in self._threads:
            remaining = None if deadline is None else max(
                0.0, deadline - time.monotonic())
            thread.join(remaining)

    def _heartbeat_loop(self) -> None:
        while not self.stop_event.wait(self.heartbeat_interval):
            # A transient store error must not kill the heartbeat
            # thread forever — that would turn one hiccup into a
            # permanently "dead" node (orphan reclaim would then
            # steal its running tasks).
            try:
                # Never-blocking duties first: the advisory heartbeat
                # publish (journals through an outage), lease renewal
                # (unwrapped — fails fast so a partitioned leader
                # abdicates honestly) and retention deletes (purely
                # local) must keep their cadence even when the store
                # is dark.
                self._heartbeat()
                self._renew_sweep_leases()
                self._sweep_retention()
            except Exception:
                logger.exception("heartbeat iteration failed; "
                                 "continuing")
            try:
                # Store-coordination duties ride a bounded critical-
                # retry window: without it, one get_entity inside a
                # store outage would park THIS thread in the
                # resilient wrapper's retry loop for up to
                # max_outage_seconds, starving every duty above —
                # the sleep-in-sweep class the lint rules forbid.
                # On the bound firing, skip the rest of the beat;
                # the next beat re-probes.
                with self._store_bounded(
                        max(5.0, 2.0 * self.heartbeat_interval)):
                    self._sweep_orphaned_gangs()
                    self._sweep_task_expansions()
                    self._sweep_preemptions()
                    self._sweep_stale_preempt_files()
                    self._forward_profile_requests()
                    self._forward_preempt_requests()
                    self._ingest_live_trace_spans()
                    self._sync_sched_hints()
            except state_resilient.StoreOutageError:
                logger.warning(
                    "store outage: coordination sweeps skipped "
                    "this beat")
            except Exception:
                logger.exception("heartbeat iteration failed; "
                                 "continuing")
        # Graceful abdication: release any held sweep leases so the
        # successor acquires immediately instead of waiting out the
        # expiry. A simulated crash (_abandoned) must NOT release —
        # a real dead process couldn't, and the failover-by-expiry
        # path is exactly what the partition drill exercises.
        if not self._abandoned:
            for lease in self._sweep_leases.values():
                try:
                    lease.release()
                except Exception:  # noqa: BLE001 - expiry reclaims
                    pass
        # Final state write must NOT resurrect a node entity the
        # substrate already deleted (teardown race) — _heartbeat
        # merges and tolerates a missing row. Best-effort: a store
        # failure here just leaves the row to go heartbeat-stale.
        try:
            self._heartbeat(state="offline")
        except Exception:
            logger.exception("final offline heartbeat failed")

    # --------------------------- work loop -----------------------------

    def _control_loop(self) -> None:
        pool_id, node_id = self._nid
        ctrlq = names.control_queue(pool_id, node_id)
        while not self.stop_event.is_set():
            try:
                msgs = self.store.get_messages(
                    ctrlq, max_messages=4, visibility_timeout=60.0)
            except Exception:
                # Same survival rule as the heartbeat loop: a store
                # hiccup must not permanently deafen the node to
                # control verbs (term_task, shutdown).
                logger.exception("control poll failed; retrying")
                time.sleep(self.poll_interval)
                continue
            for msg in msgs:
                try:
                    self._handle_control(json.loads(msg.payload))
                except Exception:
                    logger.exception("control message failed")
                self.store.delete_message(msg)
            if not msgs:
                time.sleep(self.poll_interval)

    def _worker_loop(self, slot: int) -> None:
        pool_id, node_id = self._nid
        shards = max(self.pool.task_queue_shards, 1)
        # Strict priority-band drain order (hi before normal before
        # lo): within each band, stagger each slot's starting shard so
        # pollers spread over the fan-out instead of convoying on
        # shard 0. A worker restarts its scan from the hi band after
        # every message, so a high-priority job overtakes any backlog
        # sitting in lower bands.
        bands = names.task_queues_by_band(pool_id, shards)
        stagger = self.identity.node_index + slot
        # Claim batch size: up to one message per node slot (capped)
        # per poll. A 1-slot node claims one at a time — exactly the
        # legacy behavior — while an 8-slot node amortizes the queue
        # round trip 8x. The cap bounds how long a surplus claim can
        # sit parked relative to its visibility window.
        claim_batch = max(
            1, min(int(self.pool.task_slots_per_node), 16))
        # Queue-shard autoscale pickup: the submitter may grow the
        # pool's shard fan-out mid-run (jobs/manager.py
        # maybe_autoscale_queue_shards, grow-only). Refresh the
        # cached count on a slow cadence and rebuild the band scan —
        # old shard names are a strict subset of the new set, so a
        # stale scan misses no in-flight message, it only under-uses
        # the new fan-out until the refresh lands.
        shards_checked = time.monotonic()
        # Idle-poll backoff for the hi/lo bands: most pools only ever
        # use priority 0, and probing three bands instead of one
        # every cycle would triple steady-state store traffic. A band
        # seen empty gets skipped for a growing number of cycles
        # (capped so a newly-submitted high-priority task waits at
        # most ~4 poll intervals before the scan sees it).
        skip = {0: 0, 2: 0}  # band index -> cycles left to skip
        streak = {0: 0, 2: 0}
        while not self.stop_event.is_set():
            # An adoption watcher owns this slot's capacity until the
            # adopted task finishes: polling for NEW work here would
            # oversubscribe the node past task_slots_per_node.
            if slot in self._adopted_slots:
                time.sleep(self.poll_interval)
                continue
            # Quarantined node: auto-drain means claim NOTHING — do
            # not even pop messages. Each pop would hide a message
            # from healthy nodes for a visibility window and churn
            # the store for the whole probation period. (The
            # per-message guard in _process_task_message stays as a
            # backstop for races across this check.)
            if self.node_quarantined():
                self._release_prefetched()
                time.sleep(self.poll_interval)
                continue
            if (time.monotonic() - shards_checked
                    >= _SHARD_REFRESH_SECONDS):
                shards_checked = time.monotonic()
                fresh = self._current_queue_shards(shards)
                if fresh > shards:
                    shards = fresh
                    bands = names.task_queues_by_band(pool_id, shards)
            # Drain the node-local prefetch before polling: surplus
            # claims from a prior batched poll are already invisible
            # to other nodes, so they must be worked first.
            msg = self._pop_prefetched()
            if msg is not None:
                stagger += 1
                self._dispatch_task_message(slot, msg)
                continue
            for b, band_queues in enumerate(bands):
                if b in skip and skip[b] > 0:
                    skip[b] -= 1
                    continue
                n = len(band_queues)
                found = False
                for k in range(n):
                    taskq = band_queues[(stagger + k) % n]
                    try:
                        msgs = self.store.get_messages(
                            taskq, max_messages=claim_batch,
                            visibility_timeout=(
                                self.claim_visibility_seconds))
                    except Exception:  # noqa: BLE001 - slot survives
                        # A transient store error on the poll path
                        # must not kill the worker slot forever.
                        logger.exception("queue poll failed; "
                                         "retrying")
                        msgs = []
                    if msgs:
                        msg = msgs[0]
                        if len(msgs) > 1:
                            with self._claim_prefetch_lock:
                                self._claim_prefetch.extend(msgs[1:])
                        found = True
                        break
                if b in skip:
                    if found:
                        streak[b] = 0
                    else:
                        streak[b] = min(streak[b] + 1, 4)
                        skip[b] = streak[b]
                if msg is not None:
                    break
            if msg is None:
                # Re-arm the idle marker if a failed launch path
                # cleared it without a task ever running (goodput:
                # idle time must not become unaccounted forever).
                with self._running_lock:
                    if (not self._goodput_busy_slots
                            and self._running_tasks == 0
                            and self._goodput_idle_since is None):
                        self._goodput_idle_since = time.time()
                time.sleep(self.poll_interval)
                continue
            stagger += 1
            self._dispatch_task_message(slot, msg)
        # Shutdown: surplus claims parked here would hide from the
        # rest of the pool until their visibility window lapsed.
        self._release_prefetched()

    def _dispatch_task_message(self, slot: int, msg) -> None:
        try:
            self._process_task_message(
                slot, json.loads(msg.payload), msg)
        except Exception:
            logger.exception("error processing task message; requeue")
            # Release this slot's goodput claim (idempotent; the
            # exception may have struck before or after the
            # claim) so idle accounting survives the crash.
            self._goodput_work_done(slot)
            try:
                self.store.update_message(msg, visibility_timeout=5.0)
            except Exception:  # noqa: BLE001 - slot must survive
                # A store error in the error handler must not
                # kill the worker slot; visibility timeout will
                # redeliver the message anyway.
                pass

    def _pop_prefetched(self):
        with self._claim_prefetch_lock:
            if self._claim_prefetch:
                return self._claim_prefetch.popleft()
        return None

    def _release_prefetched(self) -> None:
        """Hand surplus batched claims straight back (quarantine or
        shutdown): a parked message would otherwise stay invisible to
        healthy nodes for a full visibility window."""
        while True:
            msg = self._pop_prefetched()
            if msg is None:
                return
            try:
                self.store.update_message(msg, visibility_timeout=0.0)
            except Exception:  # noqa: BLE001 - expiry redelivers
                pass

    def _current_queue_shards(self, fallback: int) -> int:
        """The pool's current task-queue shard count via the jobs
        manager's TTL cache (one pool-entity read per TTL across
        every slot on the node)."""
        try:
            from batch_shipyard_tpu.jobs import manager as jobs_mgr
            return max(int(jobs_mgr.pool_queue_shards(
                self.store, self.identity.pool_id)), 1)
        except Exception:  # noqa: BLE001 - scan keeps old fan-out
            return fallback

    def _handle_control(self, control: dict) -> None:
        kind = control.get("type")
        if kind == "shutdown":
            self.stop_event.set()
        elif kind == "job_release":
            self._run_job_release(control["job_id"])
        elif kind == "load_images":
            self._image_manifest_cache.clear()
            if self._image_provisioner is not None:
                self._image_provisioner(
                    self, control.get("images", []),
                    kind=control.get("kind", "docker"))
        elif kind == "cleanup_mi":
            self._cleanup_mi_containers()
        elif kind == "upload_logs":
            self._upload_node_logs()
        elif kind == "install_ssh_key":
            self._install_ssh_key(control.get("username", "shipyard"),
                                  control.get("public_key", ""))
        elif kind == "remove_ssh_user":
            self._remove_ssh_user(control.get("username", "shipyard"))
        elif kind == "term_task":
            self._terminate_running_task(control["job_id"],
                                         control["task_id"])
        elif kind in ("ps", "zap", "prune"):
            # A verb that outlived its caller's wait must not execute:
            # a zap landing minutes after the operator saw "offline"
            # would kill tasks nobody asked about anymore (the reply
            # would also never be read — skip writing it).
            expires_at = control.get("expires_at")
            if expires_at is not None and time.time() > expires_at:
                logger.warning("dropping expired %s control "
                               "(%.0fs past deadline)", kind,
                               time.time() - expires_at)
                return
            if kind == "ps":
                self._control_reply(control, self._ps_report())
            elif kind == "zap":
                self._control_reply(control, self._zap())
            else:
                self._control_reply(control, self._prune_images())

    def _control_reply(self, control: dict, payload: dict) -> None:
        """Write a request/reply control verb's result to the object
        store under the caller-supplied reply key (pool/manager.py
        send_control_and_wait polls it). Fire-and-forget when the
        caller did not ask for a reply."""
        reply_key = control.get("reply_key")
        if not reply_key:
            return
        payload = dict(payload,
                       node_id=self.identity.node_id,
                       replied_at=util.datetime_utcnow_iso())
        self.store.put_object(reply_key,
                              json.dumps(payload).encode())

    def _ps_report(self) -> dict:
        """Live task/container inventory (pool nodes ps analog:
        reference docker-ps-over-ssh, convoy/fleet.py:2468 — here the
        agent answers directly over the control channel, no ssh)."""
        import shutil as shutil_mod
        tasks = []
        for (job_id, task_id), proc in list(self._live_procs.items()):
            entry = {"job_id": job_id, "task_id": task_id,
                     "pid": getattr(proc, "pid", None)}
            tasks.append(entry)
        report = {"running_tasks": tasks,
                  "task_slots": self.pool.task_slots_per_node}
        if shutil_mod.which("docker"):
            rc, out, _err = util.subprocess_capture(
                ["docker", "ps", "--filter", "name=shipyard-",
                 "--format", "{{.Names}}\t{{.Image}}\t{{.Status}}"])
            if rc == 0:
                report["containers"] = [
                    dict(zip(("name", "image", "status"),
                             line.split("\t")))
                    for line in out.splitlines() if line.strip()]
        return report

    def _zap(self) -> dict:
        """Kill every live task process group and running shipyard
        container (pool nodes zap analog, reference
        shipyard.py:1906)."""
        import shutil as shutil_mod
        import signal as signal_mod
        import subprocess as subprocess_mod
        killed = []
        for (job_id, task_id), proc in list(self._live_procs.items()):
            try:
                os.killpg(os.getpgid(proc.pid), signal_mod.SIGKILL)
                killed.append({"job_id": job_id, "task_id": task_id})
            except (ProcessLookupError, PermissionError, OSError):
                pass
        containers = []
        if shutil_mod.which("docker"):
            rc, out, _err = util.subprocess_capture(
                ["docker", "ps", "--filter", "name=shipyard-",
                 "--format", "{{.Names}}"])
            for name in (out.split() if rc == 0 else []):
                subprocess_mod.call(
                    ["docker", "kill", name],
                    stdout=subprocess_mod.DEVNULL,
                    stderr=subprocess_mod.DEVNULL)
                containers.append(name)
        return {"killed_tasks": killed, "killed_containers": containers}

    def _prune_images(self) -> dict:
        """Remove cached image tarballs whose image left the pool's
        global-resources manifest, plus `docker image prune` when
        docker is present (pool nodes prune analog, reference
        shipyard.py:1919 — TPU-native: the cascade direct-download
        cache is this node's image store when docker is absent)."""
        import shutil as shutil_mod
        import subprocess as subprocess_mod
        removed: list[str] = []
        freed = 0
        prov = self._image_provisioner
        cache_dir = getattr(prov, "_cache_dir", None)
        if prov is not None and cache_dir and os.path.isdir(cache_dir):
            keep = set()
            for row in self.store.query_entities(
                    names.TABLE_IMAGES,
                    partition_key=self.identity.pool_id):
                blob = row.get("source_blob") or ""
                if blob:
                    keep.add(os.path.basename(blob))
                    keep.add(os.path.basename(blob) + ".sif")
            for fname in os.listdir(cache_dir):
                if fname.endswith(".part") or fname in keep:
                    continue
                path = os.path.join(cache_dir, fname)
                try:
                    freed += os.path.getsize(path)
                    os.remove(path)
                    removed.append(fname)
                except OSError:
                    pass
        report = {"removed_cached": sorted(removed),
                  "freed_bytes": freed}
        if shutil_mod.which("docker"):
            rc = subprocess_mod.call(
                ["docker", "image", "prune", "-f"],
                stdout=subprocess_mod.DEVNULL,
                stderr=subprocess_mod.DEVNULL)
            report["docker_prune_rc"] = rc
        return report

    # ------------------------ task processing --------------------------

    def _task_entity(self, job_id: str, task_id: str) -> dict:
        return self.store.get_entity(
            names.TABLE_TASKS, names.task_pk(self.identity.pool_id, job_id),
            task_id)

    def _merge_task(self, job_id: str, task_id: str, patch: dict,
                    if_match: Optional[str] = None) -> str:
        return self.store.merge_entity(
            names.TABLE_TASKS, names.task_pk(self.identity.pool_id, job_id),
            task_id, patch, if_match=if_match)

    def _deps_status(self, job_id: str, spec: dict) -> str:
        """'ready' | 'wait' | 'blocked' per depends_on semantics
        (reference: batch.py:4177-4242 + exit_conditions
        dependency_action)."""
        deps = list(spec.get("depends_on", []))
        rng = spec.get("depends_on_range")
        if rng:
            deps.extend(str(i) for i in range(rng[0], rng[1] + 1))
        for dep in deps:
            try:
                ent = self._task_entity(job_id, dep)
            except NotFoundError:
                return "wait"
            state = ent.get("state")
            if state == "completed":
                continue
            if state in ("failed", "blocked",
                         names.TASK_STATE_QUARANTINED):
                dep_action = (ent.get("spec", {}).get("exit_options", {})
                              .get("dependency_action", "block"))
                if dep_action == "satisfy":
                    continue
                return "blocked"
            return "wait"
        return "ready"

    def _process_task_message(self, slot: int, payload: dict,
                              msg) -> None:
        job_id = payload["job_id"]
        task_id = payload["task_id"]
        instance = payload.get("instance")
        try:
            entity = self._task_entity(job_id, task_id)
        except NotFoundError:
            self.store.delete_message(msg)
            return
        if entity.get("state") in names.TERMINAL_TASK_STATES:
            self.store.delete_message(msg)
            return
        # Disabled jobs keep their tasks queued but unscheduled
        # (jobs disable --requeue semantics).
        job_state = self._cached_job_state(job_id)
        if job_state == "disabled":
            self.store.update_message(msg, visibility_timeout=5.0)
            return
        if job_state in ("terminated", "deleted"):
            self.store.delete_message(msg)
            return
        spec = entity["spec"]
        # Node-pinned task (federation required-target select): only
        # the named node may claim it. Everyone else makes the message
        # immediately visible again and backs off THEIR OWN polling —
        # re-hiding it for seconds would let a fast non-pinned poller
        # starve the pinned node of visibility windows.
        required = spec.get("required_node")
        if required and required != self.identity.node_id:
            # Hide only for one poll interval: long hides starve the
            # pinned node of visibility windows, while zero-hide plus
            # an in-handler sleep would park worker slots on the
            # queue-head pinned message instead of the work behind it.
            self.store.update_message(
                msg, visibility_timeout=self.poll_interval)
            return
        # Retry-supervisor backoff: a requeued task is not claimable
        # before its not_before. The requeue message already carries
        # the delay; this guards redelivered older copies of the
        # message from defeating the backoff.
        not_before = entity.get("not_before")
        if not_before and time.time() < float(not_before):
            self.store.update_message(
                msg, visibility_timeout=min(
                    5.0, max(0.1, float(not_before) - time.time())))
            return
        # Quarantined node: auto-drain. Make the message promptly
        # visible for healthy nodes and claim nothing new.
        if self.node_quarantined():
            self.store.update_message(
                msg, visibility_timeout=self.poll_interval)
            time.sleep(self.poll_interval)
            return
        # Warm-cache affinity window (shared sched/policy.py, the
        # same functions the fleet simulator prices): when this claim
        # would pay a material expected-badput cost — cold persistent
        # compile cache for the task's declared identity, degraded
        # health, recent failures — and the task is still YOUNG, hand
        # the message back briefly so a warm/healthy node can claim
        # it. Past the affinity window any node claims: deferral
        # trades bounded queueing badput for compile badput, never
        # starvation.
        if self._claim_scoring and self._should_defer_claim(entity,
                                                            spec):
            self.store.update_message(
                msg, visibility_timeout=max(0.5, min(
                    5.0,
                    self._policy_knobs.claim_affinity_wait_seconds
                    / 4.0)))
            return
        deps = self._deps_status(job_id, spec)
        if deps == "blocked":
            try:
                self._merge_task(job_id, task_id, {"state": "blocked"},
                                 if_match=entity["_etag"])
            except (EtagMismatchError, NotFoundError):
                pass
            self.store.delete_message(msg)
            return
        if deps == "wait":
            self.store.update_message(msg, visibility_timeout=1.0)
            return
        # Dead-node recovery: a redelivered message whose task is still
        # assigned/running on a node with a stale heartbeat means that
        # node died mid-task — reclaim it (the responsibility Azure
        # Batch's hosted agent handled for the reference).
        entity = self._maybe_reclaim_orphan(job_id, task_id, entity)
        if entity is None:
            self.store.update_message(msg, visibility_timeout=10.0)
            return
        if instance is None:
            self._run_regular_task(slot, job_id, task_id, entity, msg)
        else:
            self._run_gang_instance(
                slot, job_id, task_id, entity, instance, msg)

    def _should_defer_claim(self, entity: dict, spec: dict) -> bool:
        """Price THIS node's claim with the shared scoring policy and
        ask the shared affinity-window rule whether to hand the task
        back. Identical decision code to the fleet simulator's claim
        path — a simulated affinity delta is evidence about this
        function's behavior in production."""
        identity = spec.get("compile_cache_identity")
        warm = bool(identity) and identity in cc_manager.\
            list_identity_dirs(self._compile_cache_dir())
        with self._health_lock:
            health = self._health
            failures = self._recent_failures
        score = sched_policy.claim_score(
            warm=warm, health=health, recent_failures=failures,
            has_identity=bool(identity), knobs=self._policy_knobs)
        since = goodput_events.iso_to_epoch(
            entity.get("requeued_at") or entity.get("submitted_at"))
        queued = 0.0 if since is None else max(0.0,
                                               time.time() - since)
        return sched_policy.should_defer_claim(
            score, queued, knobs=self._policy_knobs)

    def _cached_job_state(self, job_id: str) -> Optional[str]:
        return self._cached_job(job_id)[0]

    def _cached_job_profile_request(self,
                                    job_id: str) -> Optional[dict]:
        """The job's pending on-demand profile request (or None);
        rides the same short-TTL cache as the disabled/terminated
        check so the heartbeat forwarding loop costs no extra store
        round trips."""
        return self._cached_job(job_id)[1]

    def _cached_job(self, job_id: str) -> tuple:
        now = time.monotonic()
        cached = self._job_state_cache.get(job_id)
        if cached is not None and now - cached[-1] < self._job_state_ttl:
            return cached
        try:
            job = self.store.get_entity(
                names.TABLE_JOBS, self.identity.pool_id, job_id)
            state = job.get("state")
            profile = job.get(trace_profiling.COL_PROFILE_REQUEST)
            if not isinstance(profile, dict):
                profile = None
        except NotFoundError:
            state = None
            profile = None
        self._job_state_cache[job_id] = (state, profile, now)
        return self._job_state_cache[job_id]

    def _maybe_reclaim_orphan(self, job_id: str, task_id: str,
                              entity: dict) -> Optional[dict]:
        """Return a claimable entity, resetting orphans to pending.

        None means the task is legitimately held by a live node (or we
        lost a reset race); the caller should back off.
        """
        state = entity.get("state")
        owner = entity.get("node_id")
        if state not in ("assigned", "running") or not owner:
            return entity
        if owner == self.identity.node_id:
            if (job_id, task_id) in self._live_procs:
                # Crash-restart ADOPTION (not reclaim): the restarted
                # agent found the pre-crash process still running and
                # adopted it (slot ledger, _adopt_restart_state).
                # Resetting here would double-run the task under its
                # own feet — back off; the adoption watcher owns the
                # completion, and the redelivered message dies on the
                # terminal-state check afterwards.
                return None
            # Our own pre-crash claim with NO surviving process:
            # take it back (the pre-adoption restart semantics).
        else:
            if self._node_alive(owner):
                return None
        logger.warning(
            "task %s/%s orphaned by %s; resetting to pending",
            job_id, task_id, owner)
        try:
            self._merge_task(
                job_id, task_id,
                {"state": "pending", "node_id": None,
                 # Queue-time accounting restarts here: the dead
                 # node's runtime is not queueing badput.
                 "requeued_at": util.datetime_utcnow_iso()},
                if_match=entity["_etag"])
        except (EtagMismatchError, NotFoundError):
            return None
        return self._task_entity(job_id, task_id)

    def _message_keepalive(self, msg, interval: Optional[float] = None,
                           visibility: Optional[float] = None):
        """Keep a claimed queue message invisible while work runs.

        Without this, a task running past the visibility timeout gets
        redelivered and double-executed (on this node if it has spare
        slots, or on another via the orphan-reclaim path). The window
        follows claim_visibility_seconds: it is also the FLOOR on
        crashed-node recovery latency (a dead node's claimed message
        only redelivers when its window lapses), which is why chaos
        drills and tests shrink it."""
        if visibility is None:
            visibility = self.claim_visibility_seconds
        if interval is None:
            interval = max(0.5, visibility / 3.0)
        stop = threading.Event()

        def _renew() -> None:
            while not stop.wait(interval):
                if self._abandoned:
                    # Simulated agent death: a dead process renews
                    # nothing — the claim must lapse so observers see
                    # the truth (the adoption watcher keeps the task,
                    # not the message).
                    return
                try:
                    self.store.update_message(
                        msg, visibility_timeout=visibility)
                except Exception:
                    return

        thread = threading.Thread(target=_renew, daemon=True)
        thread.start()

        class _Guard:
            def __enter__(self_inner):
                return self_inner

            def __exit__(self_inner, *exc):
                stop.set()
                thread.join(timeout=1.0)
                return False

        return _Guard()

    # ------------------------ goodput hooks ----------------------------

    def _goodput_work_started(self, slot: int, job_id: str,
                              task_id: str, entity: dict,
                              emit_queued: bool = True) -> None:
        """Close the node's open idle interval and emit the task's
        queueing span (submit -> first claim; requeue -> re-claim for
        retries) — the scheduling-leg badput of the decomposition.
        Gang instances pass emit_queued only for instance 0 so an
        8-wide gang doesn't report 8x queue time."""
        with self._running_lock:
            idle_since = self._goodput_idle_since
            self._goodput_idle_since = None
            self._goodput_busy_slots.add(slot)
        now = time.time()
        if idle_since is not None and now > idle_since:
            goodput_events.emit(
                self.store, self.identity.pool_id,
                goodput_events.NODE_IDLE,
                node_id=self.identity.node_id,
                start=idle_since, end=now)
        ctx = trace_context.TraceContext.from_entity(entity)
        # Claim marker: instantaneous, but it pins WHICH node won the
        # claim (and when) on the submission's causal chain.
        trace_spans.emit(
            self.store, self.identity.pool_id, trace_spans.SPAN_CLAIM,
            ctx, job_id=job_id, task_id=task_id,
            node_id=self.identity.node_id,
            attrs={"retries": entity.get("retries", 0)})
        if not emit_queued:
            return
        # A retried task waited since its REQUEUE, not its original
        # submit — the first attempt's runtime is not queue time.
        submitted = goodput_events.iso_to_epoch(
            entity.get("requeued_at") or entity.get("submitted_at"))
        if submitted is not None and now > submitted:
            goodput_events.emit(
                self.store, self.identity.pool_id,
                goodput_events.TASK_QUEUED, job_id=job_id,
                task_id=task_id, node_id=self.identity.node_id,
                start=submitted, end=now,
                attrs={"retries": entity.get("retries", 0)},
                trace_id=entity.get(trace_context.COL_TRACE_ID),
                span_id=entity.get(trace_context.COL_TRACE_SPAN))
            trace_spans.emit(
                self.store, self.identity.pool_id,
                trace_spans.SPAN_QUEUE_WAIT, ctx, job_id=job_id,
                task_id=task_id, node_id=self.identity.node_id,
                start=submitted, end=now,
                attrs={"retries": entity.get("retries", 0)})
        # Retry supervisor's deliberate backoff wait: priced on claim
        # (never at requeue — that would future-date the interval).
        # The window [requeue, not_before] sits inside the queue span
        # above; backoff outranks queueing in the overlap sweep, so
        # the deliberate wait lands in its own category without
        # double counting. A task terminated mid-backoff simply never
        # re-claims, and no unelapsed second is ever charged.
        not_before = entity.get("not_before")
        if (submitted is not None and not_before
                and entity.get("requeued_at")):
            end = min(float(not_before), now)
            if end > submitted:
                goodput_events.emit(
                    self.store, self.identity.pool_id,
                    goodput_events.TASK_BACKOFF, job_id=job_id,
                    task_id=task_id, node_id=self.identity.node_id,
                    start=submitted, end=end,
                    attrs={"retries": entity.get("retries", 0),
                           "delay_seconds": end - submitted},
                    trace_id=entity.get(trace_context.COL_TRACE_ID),
                    span_id=entity.get(trace_context.COL_TRACE_SPAN))
                trace_spans.emit(
                    self.store, self.identity.pool_id,
                    trace_spans.SPAN_BACKOFF_WAIT, ctx,
                    job_id=job_id, task_id=task_id,
                    node_id=self.identity.node_id,
                    start=submitted, end=end,
                    attrs={"retries": entity.get("retries", 0)})
        # Preemption-recovery interval: preempted exit -> this claim.
        # Priced once per preemption (the claim patch clears
        # preempted_at; gang width dedup rides the emit_queued flag,
        # so an 8-wide gang reports the leg once). This is the badput
        # every preemption actually costs — the drill's
        # "preemption_recovery now populated" acceptance.
        preempted_at = entity.get(names.TASK_COL_PREEMPTED_AT)
        if preempted_at and now > float(preempted_at):
            goodput_events.emit(
                self.store, self.identity.pool_id,
                goodput_events.TASK_PREEMPT_RECOVERY, job_id=job_id,
                task_id=task_id, node_id=self.identity.node_id,
                start=float(preempted_at), end=now,
                attrs={"preempt_count": entity.get(
                    names.TASK_COL_PREEMPT_COUNT, 0)},
                trace_id=entity.get(trace_context.COL_TRACE_ID),
                span_id=entity.get(trace_context.COL_TRACE_SPAN))
        # Eviction-recovery interval: hard-killed exit -> this claim,
        # priced as the distinct `eviction` leg (same claim-side,
        # once-per-eviction protocol as the preemption leg above).
        evicted_at = entity.get(names.TASK_COL_EVICTED_AT)
        if evicted_at and now > float(evicted_at):
            goodput_events.emit(
                self.store, self.identity.pool_id,
                goodput_events.TASK_EVICTION_RECOVERY, job_id=job_id,
                task_id=task_id, node_id=self.identity.node_id,
                start=float(evicted_at), end=now,
                attrs={"evict_count": entity.get(
                    names.TASK_COL_EVICT_COUNT, 0)},
                trace_id=entity.get(trace_context.COL_TRACE_ID),
                span_id=entity.get(trace_context.COL_TRACE_SPAN))

    def _ensure_images_timed(self, job_id: str, task_id: str,
                             spec: dict,
                             entity: Optional[dict] = None) -> None:
        """_ensure_images under an image_pull goodput span (only when
        the task actually names a container image)."""
        if spec.get("image") and spec.get("runtime") in (
                "docker", "singularity"):
            entity = entity or {}
            with goodput_events.span(
                    self.store, self.identity.pool_id,
                    goodput_events.TASK_IMAGE_PULL, job_id=job_id,
                    task_id=task_id, node_id=self.identity.node_id,
                    attrs={"image": spec.get("image")},
                    trace_id=entity.get(trace_context.COL_TRACE_ID),
                    span_id=entity.get(trace_context.COL_TRACE_SPAN)):
                self._ensure_images(spec)
        else:
            self._ensure_images(spec)

    def _goodput_task_finished(self, slot: int, job_id: str,
                               task_id: str,
                               result: task_runner.TaskResult,
                               entity: Optional[dict] = None,
                               instance: Optional[int] = None) -> None:
        entity = entity or {}
        started = goodput_events.iso_to_epoch(result.started_at)
        if started is not None and result.wall_seconds > 0:
            goodput_events.emit(
                self.store, self.identity.pool_id,
                goodput_events.TASK_RUNNING, job_id=job_id,
                task_id=task_id, node_id=self.identity.node_id,
                start=started, end=started + result.wall_seconds,
                attrs={"exit_code": result.exit_code,
                       "timed_out": result.timed_out},
                trace_id=entity.get(trace_context.COL_TRACE_ID),
                span_id=entity.get(trace_context.COL_TRACE_SPAN))
            # The task's ROOT span (the id every program phase inside
            # the process parented under via $SHIPYARD_TRACE_SPAN_ID)
            # is recorded as the run span itself: launch -> exit.
            # Gang instances share the root id; only instance 0
            # writes it (one row), the rest annotate via attrs on
            # their own child spans.
            ctx = trace_context.TraceContext.from_entity(entity)
            if ctx is not None and (instance is None or instance == 0):
                trace_spans.emit(
                    self.store, self.identity.pool_id,
                    trace_spans.SPAN_TASK_RUN, ctx, job_id=job_id,
                    task_id=task_id, node_id=self.identity.node_id,
                    start=started, end=started + result.wall_seconds,
                    attrs={"exit_code": result.exit_code,
                           "wedged": result.wedged,
                           "retries": entity.get("retries", 0)},
                    self_span=True)
        self._goodput_work_done(slot)

    def _goodput_work_done(self, slot: int) -> None:
        """Release a slot's claimed-work unit (idempotent — safe to
        call from exception handlers that can't know whether the
        claim happened); re-arm the idle marker once the node has
        NOTHING claimed or running."""
        with self._running_lock:
            self._goodput_busy_slots.discard(slot)
            if (not self._goodput_busy_slots
                    and self._running_tasks == 0
                    and self._goodput_idle_since is None):
                self._goodput_idle_since = time.time()

    def _ingest_goodput(self, job_id: str, task_id: str,
                        execution: task_runner.TaskExecution) -> None:
        """Fold the task's process-local program-phase events (compile
        / step windows / checkpoint spans the workload recorded to
        $SHIPYARD_GOODPUT_FILE) into the store with the task's
        identity attached."""
        path = execution.env.get(goodput_events.GOODPUT_FILE_ENV)
        if path:
            count = goodput_events.ingest_local_events(
                self.store, self.identity.pool_id, path, job_id=job_id,
                task_id=task_id, node_id=self.identity.node_id)
            if count:
                logger.debug("ingested %d goodput events from %s/%s",
                             count, job_id, task_id)
        # Trace spans ride the same post-task ingest: program spans
        # the workload recorded to $SHIPYARD_TRACE_FILE join the
        # submission's trace in TABLE_TRACE. Rename-first (the same
        # protocol as the heartbeat drain) so a drain racing this
        # exit path can never ingest the same lines twice — exactly
        # one reader wins any given inode.
        trace_path = execution.env.get(trace_context.TRACE_FILE_ENV)
        if trace_path:
            count = self._drain_trace_file(trace_path, job_id,
                                           task_id)
            if count:
                logger.debug("ingested %d trace spans from %s/%s",
                             count, job_id, task_id)

    def _drain_trace_file(self, path: str, job_id: str,
                          task_id: str) -> int:
        """Atomically claim and ingest one trace-span JSONL. The
        os.replace is the mutual exclusion between the heartbeat
        drain and the post-task ingest: a loser gets ENOENT and
        ingests nothing; a writer mid-append follows the inode into
        the renamed file (still ingested), and the recorder's next
        append re-creates the original path."""
        if not os.path.exists(path):
            return 0
        drained = f"{path}.{uuid.uuid4().hex[:6]}.ingest"
        try:
            os.replace(path, drained)
        except OSError:
            return 0
        return trace_spans.ingest_local_spans(
            self.store, self.identity.pool_id, drained,
            job_id=job_id, task_id=task_id,
            node_id=self.identity.node_id)

    def _ingest_live_trace_spans(self) -> None:
        """Drain LIVE tasks' trace-span JSONL mid-run, so long-lived
        serving tasks feed heimdall's latency export while running
        instead of only at exit. The drain is an atomic rename: a
        writer mid-append follows the inode into the renamed file
        (still ingested), and the recorder's next append re-creates
        the original path — no line is ever lost or read twice."""
        for job_id, task_id in list(self._live_procs.keys()):
            root = os.path.join(self.work_dir, "tasks", job_id,
                                task_id)
            candidates = [os.path.join(root, "trace_spans.jsonl")]
            try:
                candidates += [
                    os.path.join(root, d, "trace_spans.jsonl")
                    for d in os.listdir(root) if d.startswith("i")]
            except OSError:
                continue
            for path in candidates:
                self._drain_trace_file(path, job_id, task_id)

    def _sync_sched_hints(self) -> None:
        """Mirror LIVE tasks' sched-hints files
        (agent/progress.py record_sched_hints) into their task rows'
        sched_hints column, where the preemption sweep's shared
        victim-cost policy prices replay rework. Advisory and cheap:
        one local read per live task per beat, a store write only
        when the hints CHANGED (a step-cadenced writer is throttled
        by content, not another timer). For a gang, the instance
        with the highest step wins — rework is priced by the
        furthest-ahead shard that would replay."""
        for job_id, task_id in list(self._live_procs.keys()):
            root = os.path.join(self.work_dir, "tasks", job_id,
                                task_id)
            candidates = [os.path.join(root, "sched_hints.json")]
            try:
                candidates += [
                    os.path.join(root, d, "sched_hints.json")
                    for d in os.listdir(root) if d.startswith("i")]
            except OSError:
                continue
            best: Optional[dict] = None
            for path in candidates:
                hints = progress_mod.read_sched_hints(path)
                if hints is None:
                    continue
                if best is None or (hints.get("step") or 0) > \
                        (best.get("step") or 0):
                    best = hints
            if best is None:
                continue
            fingerprint = json.dumps(best, sort_keys=True)
            key = (job_id, task_id)
            if self._sched_hints_sent.get(key) == fingerprint:
                continue
            try:
                self._merge_task(job_id, task_id,
                                 {names.TASK_COL_SCHED_HINTS: best})
                self._sched_hints_sent[key] = fingerprint
            except (NotFoundError, EtagMismatchError):
                continue

    # ----------------------- profiling hooks ---------------------------

    def _forward_profile_requests(self) -> None:
        """On-demand profiling, mid-run leg: the heartbeat loop drops
        the job's pending profile request into the task dirs of this
        node's LIVE tasks (launch-time delivery covers tasks that
        start after the flag was set). One delivery per (task,
        request): the harness consumes the file when capture starts,
        and re-dropping it would trigger a second capture."""
        for job_id, task_id in list(self._live_procs.keys()):
            request = self._cached_job_profile_request(job_id)
            if request is None:
                continue
            self._deliver_profile_request(job_id, task_id, request)

    def _deliver_profile_request(self, job_id: str, task_id: str,
                                 request: dict) -> None:
        for task_dir in self._task_dir_targets(job_id, task_id):
            self._deliver_profile_file(
                os.path.join(task_dir, "profile_request.json"),
                request)

    def _deliver_profile_file(self, path: str,
                              request: dict) -> None:
        """Write one request file, deduped per (path, request). The
        delivered mark is taken only AFTER a successful write, so a
        transient OSError retries on the next heartbeat instead of
        silently losing the request forever. A sibling ``.delivered``
        marker persists the dedup across agent restarts — without it
        a restarted agent would re-drop a request the harness already
        consumed and trigger a second capture."""
        requested_at = str(request.get("requested_at"))
        key = (path, requested_at)
        if key in self._profile_delivered:
            return
        marker = path + ".delivered"
        try:
            with open(marker, encoding="utf-8") as fh:
                if fh.read().strip() == requested_at:
                    self._profile_delivered.add(key)
                    return
        except OSError:
            pass
        try:
            steps = max(1, int(request.get("steps", 1)))
        except (TypeError, ValueError):
            steps = 1
        try:
            trace_profiling.write_request(
                path, steps,
                requested_at=request.get("requested_at"))
            with open(marker, "w", encoding="utf-8") as fh:
                fh.write(requested_at)
        except OSError:
            logger.debug("profile request delivery failed for %s",
                         path, exc_info=True)
            return
        # Bound the in-memory set (the disk markers keep the dedup):
        # a long-lived agent across many `jobs profile` invocations
        # must not grow it forever.
        if len(self._profile_delivered) > 4096:
            self._profile_delivered.clear()
        self._profile_delivered.add(key)

    def _upload_profile_artifacts(self, job_id: str, task_id: str,
                                  execution: task_runner.TaskExecution,
                                  suffix: str = "") -> None:
        """Post-task: ship the jax.profiler capture (if one was
        taken) through the store next to the task's other outputs and
        stamp the artifact prefix on the task entity, where
        ``jobs tasks list`` surfaces it."""
        profile_dir = execution.env.get(
            trace_profiling.PROFILE_DIR_ENV)
        if not profile_dir or not os.path.isdir(profile_dir):
            return
        uploaded = 0
        prefix = f"{suffix}/profile" if suffix else "profile"
        for root, _dirs, files in os.walk(profile_dir):
            for name in files:
                path = os.path.join(root, name)
                rel = os.path.relpath(path, profile_dir)
                try:
                    with open(path, "rb") as fh:
                        self.store.put_object(
                            names.task_output_key(
                                self.identity.pool_id, job_id,
                                task_id, f"{prefix}/{rel}"),
                            fh.read())
                    uploaded += 1
                except Exception:  # noqa: BLE001 - best effort
                    logger.exception("profile artifact upload failed "
                                     "for %s", path)
        if not uploaded:
            return
        try:
            self._merge_task(job_id, task_id, {
                trace_profiling.COL_PROFILE_ARTIFACT:
                    names.task_output_key(
                        self.identity.pool_id, job_id, task_id,
                        prefix),
                "profile_files": uploaded,
            })
        except NotFoundError:
            pass
        logger.info("uploaded %d profile file(s) for %s/%s",
                    uploaded, job_id, task_id)

    # ---------------------- preemption scheduling ----------------------

    def _sweep_preemptions(self) -> None:
        """Numeric-priority preemption sweep (leader-gated, like the
        gang janitor — one unpartitioned task scan per pool per
        interval). A pending task that has waited past the grace
        window while STRICTLY lower-priority work runs cannot place:
        the sweep elects the lowest-priority running victim (gangs
        included — one stamped entity preempts every instance) and
        stamps a cooperative preempt request on it. The victim's
        agent delivers the request over the heartbeat path, the
        workload drains to a step boundary, commits, and exits
        EXIT_PREEMPTED — requeued at full budget. One victim per
        starved task per sweep: cooperative preemption converges over
        sweeps instead of mass-evicting a pool in one pass.

        ESCALATION (the same scan): a victim whose pending request is
        older than preempt_grace_seconds never drained — the sweep
        stamps the request escalated, and the owning node's heartbeat
        loop hard-kills the process (_enforce_eviction). The exit is
        then classified `evicted`: claimable at full budget like
        `preempted`, but resuming from the last COMMITTED checkpoint
        BEFORE the notice, and priced as the distinct `eviction`
        badput leg."""
        if self.preempt_sweep_interval <= 0:
            return
        if (time.monotonic() - self._last_preempt_sweep
                < self.preempt_sweep_interval):
            return
        self._last_preempt_sweep = time.monotonic()
        epoch = self._sweep_leader_epoch(
            state_leases.ROLE_PREEMPT_SWEEP)
        if epoch is None:
            return
        lease = self._sweep_lease(state_leases.ROLE_PREEMPT_SWEEP)
        prefix = f"{self.identity.pool_id}$"
        now = time.time()
        starved: list[tuple] = []   # (priority, waited_since, row)
        victims: list[tuple] = []   # (priority, row)
        for row in self.store.query_entities(names.TABLE_TASKS):
            if not row["_pk"].startswith(prefix):
                continue
            state = row.get("state")
            priority = int(
                (row.get("spec") or {}).get("priority", 0) or 0)
            if state in names.CLAIMABLE_TASK_STATES:
                not_before = row.get("not_before")
                if not_before and now < float(not_before):
                    continue  # deliberate backoff, not starvation
                since = goodput_events.iso_to_epoch(
                    row.get("requeued_at") or row.get("submitted_at"))
                if since is None or \
                        now - since < self.preempt_grace_seconds:
                    continue
                starved.append((priority, since, row))
            elif state in ("assigned", "running"):
                request = row.get(names.TASK_COL_PREEMPT_REQUEST)
                if isinstance(request, dict):
                    # Already draining — unless the notice lapsed, in
                    # which case the ladder's next rung fires: stamp
                    # the escalation so the owning node hard-kills.
                    # Fenced like every other sweep write.
                    if not lease.fenced(epoch):
                        return
                    self._maybe_escalate_eviction(row, request, now,
                                                  leader_epoch=epoch)
                    continue
                if request:
                    continue  # malformed stamp; never a victim twice
                # Goodput-cost victim ordering (shared
                # sched/policy.py, the functions the fleet simulator
                # prices): lowest priority first, then CHEAPEST
                # expected rework — replay steps past the last
                # committed checkpoint plus warm compile state
                # destroyed, from the sched_hints column the
                # heartbeat mirrors — then task id. Hint-less tasks
                # price 0.0, so the order degrades to the
                # deterministic (priority, task_id) tie-break instead
                # of scan order (dict/row order must never elect a
                # victim).
                cost = sched_policy.victim_cost_from_row(
                    row, knobs=self._policy_knobs)
                victims.append((sched_policy.victim_sort_key(
                    priority, cost, row["_rk"]), row))
        if not starved or not victims:
            return
        starved.sort(key=lambda t: (-t[0], t[1]))
        victims.sort(key=lambda t: t[0])
        from batch_shipyard_tpu.jobs import manager as jobs_mgr
        for priority, _since, row in starved:
            if not victims or victims[0][0][0] >= priority:
                break  # nothing running is strictly lower anymore
            # Fencing re-check BEFORE each stamp (satellite audit):
            # the scan above can outlive the term, and a preemption
            # stamp is NOT idempotent across two leaders — two terms
            # electing different victims for the same starved task is
            # exactly the double-fire the partition drill forbids.
            if not lease.fenced(epoch):
                return
            victim_key, victim = victims.pop(0)
            victim_priority = victim_key[0]
            victim_job = victim["_pk"][len(prefix):]
            starved_job = row["_pk"][len(prefix):]
            stamped = jobs_mgr.request_preemption(
                self.store, self.identity.pool_id, victim_job,
                victim["_rk"],
                reason=(f"priority {priority} task "
                        f"{starved_job}/{row['_rk']} cannot place "
                        f"(victim priority {victim_priority})"),
                by_job_id=starved_job, by_task_id=row["_rk"],
                leader_epoch=epoch, defer_notice=True)
            if stamped and not lease.fenced(epoch):
                # The pre-write fence cannot bound the WRITE's own
                # latency: under store retries the merge can land
                # after our term ended, while the successor elects a
                # DIFFERENT victim for the same starved task. The
                # author is the only party that can tell "issued in
                # term E, landed late" apart from a legitimate term-E
                # stamp — so it retracts its own late stamp. The
                # notice was deferred, so the retraction leaves no
                # dangling TASK_PREEMPT_NOTICE event behind either.
                self._retract_stale_preempt_stamp(
                    victim["_pk"], victim["_rk"], epoch)
                return
            if callable(stamped):
                stamped()  # the stamp stands: publish its notice

    def _retract_stale_preempt_stamp(self, pk: str, rk: str,
                                     epoch: int) -> None:
        """Undo OUR OWN preemption stamp that landed after the term
        ended (write latency outlived the lease margin). Only a
        still-unescalated request carrying exactly our epoch is
        retracted; anything else means the world moved on."""
        try:
            row = self.store.get_entity(names.TABLE_TASKS, pk, rk)
        except Exception:  # noqa: BLE001 - stamp stays attributable
            logger.warning("could not retract stale preempt stamp "
                           "for %s/%s", pk, rk, exc_info=True)
            return
        request = row.get(names.TASK_COL_PREEMPT_REQUEST)
        if not (isinstance(request, dict)
                and request.get("leader_epoch") == epoch
                and not request.get("escalated_at")):
            return
        try:
            self.store.merge_entity(
                names.TABLE_TASKS, pk, rk,
                {names.TASK_COL_PREEMPT_REQUEST: None},
                if_match=row["_etag"])
            logger.warning(
                "retracted preempt stamp on %s/%s: it landed after "
                "leadership term %d ended", pk, rk, epoch)
        except (EtagMismatchError, NotFoundError):
            pass  # a concurrent transition owns the row now
        except Exception:  # noqa: BLE001 - best effort
            logger.warning("could not retract stale preempt stamp "
                           "for %s/%s", pk, rk, exc_info=True)

    def _forward_preempt_requests(self) -> None:
        """Heartbeat-loop delivery of pending preempt requests into
        this node's LIVE tasks' dirs (the profile-request channel):
        one short-TTL-cached entity read per live task, one file drop
        per (target, requested_at). Stale-epoch stamps are held for
        one confirmation cycle before delivery (see
        _confirm_stale_epoch_request)."""
        for job_id, task_id in list(self._live_procs.keys()):
            request = self._cached_task_preempt_request(job_id,
                                                        task_id)
            if not isinstance(request, dict):
                continue
            request = self._confirm_stale_epoch_request(
                job_id, task_id, request)
            if request is None:
                continue
            self._deliver_preempt_request(job_id, task_id, request)
            # Escalation enforcement is LOCAL: the leader stamped the
            # decision on the entity; only the node holding the live
            # process can actually kill it (gang instances each die
            # on their own node).
            if request.get("escalated_at"):
                self._enforce_eviction(job_id, task_id)

    def _maybe_escalate_eviction(self, row: dict, request: dict,
                                 now: float,
                                 leader_epoch: Optional[int] = None,
                                 ) -> None:
        """Leader-side escalation decision: a pending preempt request
        older than preempt_grace_seconds means the victim ignored its
        notice — stamp ``escalated_at`` on the request (etag-guarded,
        exactly one escalation per request) so the owning node's
        heartbeat loop hard-kills it. The stamp is what classifies
        the subsequent exit as ``evicted`` rather than a failure.
        ``leader_epoch`` (the sweep term's fencing epoch) rides the
        stamp so a deposed leader's in-flight escalation is
        attributable — and its etag merge loses cleanly to any write
        the successor landed first."""
        if request.get("escalated_at"):
            return
        requested = goodput_events.iso_to_epoch(
            request.get("requested_at"))
        if requested is None or \
                now - requested <= self.preempt_grace_seconds:
            return
        pk_parts = row["_pk"].split("$", 1)
        job_id = pk_parts[1] if len(pk_parts) == 2 else row["_pk"]
        try:
            self.store.merge_entity(
                names.TABLE_TASKS, row["_pk"], row["_rk"],
                {names.TASK_COL_PREEMPT_REQUEST: {
                    **request,
                    "escalated_at": util.datetime_utcnow_iso(),
                    "leader_epoch": leader_epoch}},
                if_match=row["_etag"])
        except (EtagMismatchError, NotFoundError):
            return  # a concurrent transition (e.g. the drain) won
        logger.warning(
            "task %s/%s ignored its preempt notice for %.1fs "
            "(grace %.1fs); escalating to forcible eviction",
            job_id, row["_rk"], now - requested,
            self.preempt_grace_seconds)

    def _enforce_eviction(self, job_id: str, task_id: str) -> None:
        """Hard-kill an escalated victim's live process group on THIS
        node: docker containers are force-removed first (SIGKILL is
        never proxied by the docker client — the task_runner wedge
        lesson), then the group eats SIGKILL. The local marker makes
        the completion path classify the exit as evicted."""
        key = (job_id, task_id)
        proc = self._live_procs.get(key)
        if proc is None or key in self._evicted_locally:
            return
        self._evicted_locally.add(key)
        logger.warning("evicting %s/%s: hard kill after ignored "
                       "preempt notice", job_id, task_id)
        self._hard_kill_task_group(job_id, task_id, proc.pid)

    def _sweep_stale_preempt_files(self) -> None:
        """Per-node janitor for stale preempt-request files: an
        EVICTED (never-drained) task's request file + .delivered
        marker are only cleaned at next-attempt launch on the same
        node — a node that never reclaims the task would leak them
        forever (and its in-memory dedup key with them). Sweep this
        node's task dirs on the gang-janitor cadence: any request
        file whose task is not live here and no longer pending a
        request (terminal, gone, re-owned, or already re-requested
        under a newer requested_at) is garbage."""
        if (time.monotonic() - self._last_preempt_file_sweep
                < self.gang_sweep_interval):
            return
        self._last_preempt_file_sweep = time.monotonic()
        root = os.path.join(self.work_dir, "tasks")
        if not os.path.isdir(root):
            return
        for job_id in os.listdir(root):
            job_dir = os.path.join(root, job_id)
            if not os.path.isdir(job_dir):
                continue
            for task_id in os.listdir(job_dir):
                if (job_id, task_id) in self._live_procs:
                    continue  # delivery may still be in flight
                targets = self._task_dir_targets(job_id, task_id)
                paths = [os.path.join(d, "preempt_request.json")
                         for d in targets]
                if not any(os.path.exists(p) or
                           os.path.exists(p + ".delivered")
                           for p in paths):
                    continue
                try:
                    entity = self._task_entity(job_id, task_id)
                    pending = entity.get(
                        names.TASK_COL_PREEMPT_REQUEST)
                    stale = (
                        entity.get("state")
                        in names.TERMINAL_TASK_STATES
                        or entity.get("node_id")
                        != self.identity.node_id
                        or not isinstance(pending, dict))
                except NotFoundError:
                    stale = True
                except Exception:  # noqa: BLE001 - janitor survives
                    logger.debug("preempt-file sweep probe failed",
                                 exc_info=True)
                    continue
                if not stale:
                    continue
                for path in paths:
                    for victim in (path, path + ".delivered"):
                        try:
                            os.remove(victim)
                        except OSError:
                            pass
                    self._preempt_delivered = {
                        k for k in self._preempt_delivered
                        if k[0] != path}

    def _cached_task_preempt_request(self, job_id: str,
                                     task_id: str) -> Optional[dict]:
        """The task's pending preempt request (or None), cached for
        _job_state_ttl so the common no-preemption case costs no
        store round trip per live task per beat."""
        key = (job_id, task_id)
        now = time.monotonic()
        cached = self._task_preempt_cache.get(key)
        if cached is not None and now - cached[1] < self._job_state_ttl:
            return cached[0]
        request = None
        try:
            entity = self._task_entity(job_id, task_id)
            request = entity.get(names.TASK_COL_PREEMPT_REQUEST)
        except NotFoundError:
            pass
        except Exception:  # noqa: BLE001 - heartbeat survives
            logger.debug("preempt forward probe failed",
                         exc_info=True)
            return None  # transient: do not cache, retry next beat
        if len(self._task_preempt_cache) > 256:
            self._task_preempt_cache.clear()
        self._task_preempt_cache[key] = (request, now)
        return request

    def _confirm_stale_epoch_request(self, job_id: str, task_id: str,
                                     request: dict
                                     ) -> Optional[dict]:
        """Consumer-side fence for the author-retraction race: a
        request stamped with a leader_epoch OLDER than the preempt
        sweep's current term is exactly the shape of a deposed
        leader's late-landing stamp — which its author is about to
        retract (_retract_stale_preempt_stamp). Delivering it in
        that window drains a victim for a decision that no longer
        stands, while the successor may stamp a DIFFERENT victim for
        the same starved task: a double drain the partition drill's
        notice count cannot see, because the deferred notice was
        never published. A stale epoch alone is NOT proof of a bad
        stamp, though — a legitimate term-E stamp survives into term
        E+1 whenever leadership turns over mid-drain, and the
        successor deliberately escalates rather than re-stamps it
        (the "already draining" branch of the sweep). So first
        delivery of a stale-epoch stamp is HELD for one confirmation
        cycle, then re-read fresh: a retracted stamp has vanished; a
        stamp that survives confirmation is the world's will and is
        delivered. Current-term and epoch-less (manual ``jobs
        preempt``) stamps pass straight through."""
        stamp_epoch = request.get("leader_epoch")
        if stamp_epoch is None:
            return request
        leader = self._observed_preempt_leader()
        if (leader is None or leader.get("epoch") is None
                or stamp_epoch >= leader["epoch"]):
            return request
        key = (job_id, task_id, str(request.get("requested_at")),
               bool(request.get("escalated_at")))
        now = time.monotonic()
        first_seen = self._preempt_forward_hold.get(key)
        if first_seen is None:
            if len(self._preempt_forward_hold) > 256:
                self._preempt_forward_hold.clear()
            self._preempt_forward_hold[key] = now
            return None
        if now - first_seen < max(self.heartbeat_interval, 0.5):
            return None
        try:
            entity = self._task_entity(job_id, task_id)
        except NotFoundError:
            self._preempt_forward_hold.pop(key, None)
            return None
        except Exception:  # noqa: BLE001 - heartbeat survives
            return None  # transient: hold stands, retry next beat
        fresh = entity.get(names.TASK_COL_PREEMPT_REQUEST)
        if not (isinstance(fresh, dict)
                and fresh.get("requested_at")
                == request.get("requested_at")):
            # Retracted (or replaced): the hold did its job — drop
            # the cached copy so the next beat sees the fresh world.
            self._preempt_forward_hold.pop(key, None)
            self._task_preempt_cache.pop((job_id, task_id), None)
            logger.warning(
                "held stale-epoch preempt stamp on %s/%s was "
                "retracted before delivery (epoch %s < current %s)",
                job_id, task_id, stamp_epoch, leader["epoch"])
            return None
        self._preempt_forward_hold.pop(key, None)
        return fresh

    def _observed_preempt_leader(self) -> Optional[dict]:
        """Observer view of the preempt-sweep lease's current term,
        cached for _job_state_ttl — the epoch comparison above runs
        every beat for as long as any live task is draining, and must
        not cost two store reads each time."""
        now = time.monotonic()
        cached = self._preempt_leader_cache
        if cached is not None and now - cached[1] < self._job_state_ttl:
            return cached[0]
        leader = state_leases.read_leader(
            self.store,
            names.leader_epoch_key(self.identity.pool_id,
                                   state_leases.ROLE_PREEMPT_SWEEP))
        self._preempt_leader_cache = (leader, now)
        return leader

    def _escalated_request_pending(self, job_id: str,
                                   task_id: str) -> bool:
        """True when the task's pending preempt request carries the
        sweep's escalation stamp — the durable classification signal
        for an evicted exit (one cached entity read)."""
        request = self._cached_task_preempt_request(job_id, task_id)
        return (isinstance(request, dict)
                and bool(request.get("escalated_at")))

    def _task_dir_targets(self, job_id: str,
                          task_id: str) -> list[str]:
        """A task's dir plus its gang-instance subdirs — every
        location a per-task request file (profile, preempt) must land
        in on this node."""
        root = os.path.join(self.work_dir, "tasks", job_id, task_id)
        targets = [root]
        try:
            targets += [os.path.join(root, d)
                        for d in os.listdir(root)
                        if d.startswith("i")
                        and os.path.isdir(os.path.join(root, d))]
        except OSError:
            pass
        return [t for t in targets if os.path.isdir(t)]

    def _deliver_preempt_request(self, job_id: str, task_id: str,
                                 request: dict) -> None:
        for task_dir in self._task_dir_targets(job_id, task_id):
            self._deliver_preempt_file(
                os.path.join(task_dir, "preempt_request.json"),
                request)

    def _deliver_preempt_file(self, path: str, request: dict) -> None:
        """One request file per (path, requested_at) — the profile
        delivery protocol: a persisted .delivered marker survives
        agent restarts (a re-dropped request after the harness
        consumed it would trigger a second drain of the RERUN), and
        the mark is taken only after a successful write so transient
        OSErrors retry next heartbeat."""
        requested_at = str(request.get("requested_at"))
        key = (path, requested_at)
        if key in self._preempt_delivered:
            return
        marker = path + ".delivered"
        try:
            with open(marker, encoding="utf-8") as fh:
                if fh.read().strip() == requested_at:
                    self._preempt_delivered.add(key)
                    return
        except OSError:
            pass
        try:
            preempt_mod.write_request(
                path, reason=str(request.get("reason") or ""),
                requested_at=request.get("requested_at"),
                by_job_id=request.get("by_job_id"),
                by_task_id=request.get("by_task_id"))
            with open(marker, "w", encoding="utf-8") as fh:
                fh.write(requested_at)
        except OSError:
            logger.debug("preempt request delivery failed for %s",
                         path, exc_info=True)
            return
        if len(self._preempt_delivered) > 4096:
            self._preempt_delivered.clear()
        self._preempt_delivered.add(key)
        logger.warning("preempt request delivered to %s", path)

    def _requeue_preempted(self, job_id: str, task_id: str,
                           spec: dict,
                           instances: Optional[int] = None) -> bool:
        """Preempted requeue: the task drained cooperatively, so this
        is a scheduling transition, not a failure — the retry counter
        is NOT bumped (full budget preserved), no backoff is stamped
        (the wait was deliberate on the scheduler's side, not the
        task's), and any stale not_before from an earlier failure is
        cleared. The entity passes through the distinct ``preempted``
        state, which the claim path treats like pending; the rerun's
        restore pulls the forced COMMITTED checkpoint. Returns False
        when a concurrent transition won the merge."""
        now = time.time()
        try:
            entity = self._task_entity(job_id, task_id)
        except NotFoundError:
            return False
        if entity.get("state") in names.TERMINAL_TASK_STATES:
            return False
        request = entity.get(names.TASK_COL_PREEMPT_REQUEST)
        if not isinstance(request, dict):
            # EXIT_PREEMPTED with NO pending preempt request is not a
            # preemption: a buggy task exiting 75 unprompted would
            # otherwise requeue at full budget forever. The caller
            # falls back to the retry supervisor (budgeted).
            logger.warning(
                "task %s/%s exited with the preempted status but no "
                "preempt request is pending; treating as a failure",
                job_id, task_id)
            return False
        count = int(
            entity.get(names.TASK_COL_PREEMPT_COUNT, 0) or 0) + 1
        try:
            self._merge_task(job_id, task_id, {
                "state": names.TASK_STATE_PREEMPTED,
                "node_id": None,
                names.TASK_COL_PREEMPTED_AT: now,
                names.TASK_COL_PREEMPT_COUNT: count,
                names.TASK_COL_PREEMPT_REQUEST: None,
                "not_before": None,
                "requeued_at": util.datetime_utcnow_iso(),
            }, if_match=entity["_etag"])
        except (EtagMismatchError, NotFoundError):
            return False
        goodput_events.emit(
            self.store, self.identity.pool_id,
            goodput_events.TASK_PREEMPT_EXIT, job_id=job_id,
            task_id=task_id, node_id=self.identity.node_id,
            attrs={"preempt_count": count,
                   "reason": request.get("reason")},
            trace_id=entity.get(trace_context.COL_TRACE_ID),
            span_id=entity.get(trace_context.COL_TRACE_SPAN))
        # The cooperative window (notice -> drained exit) on the
        # trace: how long the drain + forced commit actually took.
        requested = goodput_events.iso_to_epoch(
            request.get("requested_at"))
        trace_spans.emit(
            self.store, self.identity.pool_id,
            trace_spans.SPAN_PREEMPT,
            trace_context.TraceContext.from_entity(entity),
            job_id=job_id, task_id=task_id,
            node_id=self.identity.node_id,
            start=(requested if requested and requested < now
                   else now),
            end=now,
            attrs={"preempt_count": count,
                   "reason": request.get("reason")})
        queue = names.task_queue_for(
            self.identity.pool_id, task_id,
            self.pool.task_queue_shards,
            priority=int(spec.get("priority", 0) or 0))
        message = {"job_id": job_id, "task_id": task_id}
        if entity.get(trace_context.COL_TRACE_ID):
            message["trace_id"] = entity[trace_context.COL_TRACE_ID]
        if instances:
            self.store.put_messages(
                queue,
                [json.dumps({**message, "instance": k}).encode()
                 for k in range(instances)])
        else:
            self.store.put_message(queue,
                                   json.dumps(message).encode())
        logger.warning(
            "task %s/%s preempted (count %d); requeued at full "
            "retry budget", job_id, task_id, count)
        return True

    def _requeue_evicted(self, job_id: str, task_id: str,
                         spec: dict,
                         instances: Optional[int] = None) -> bool:
        """Evicted requeue: the victim ignored its notice and was
        hard-killed after the grace window. Externally caused — so,
        like a preemption, the retry counter is untouched (full
        budget), no backoff is stamped, and node health is never
        debited. UNLIKE a preemption the drain never happened: the
        rerun resumes from the last COMMITTED checkpoint BEFORE the
        notice, and the steps since that barrier are replayed — the
        rework the distinct `eviction` badput leg prices. Requires a
        pending ESCALATED preempt request on the entity (the sweep's
        stamp is the classification); returns False otherwise so the
        caller falls back to the retry supervisor."""
        now = time.time()
        try:
            entity = self._task_entity(job_id, task_id)
        except NotFoundError:
            return False
        if entity.get("state") in names.TERMINAL_TASK_STATES:
            return False
        request = entity.get(names.TASK_COL_PREEMPT_REQUEST)
        if not isinstance(request, dict) or \
                not request.get("escalated_at"):
            # A hard-killed exit WITHOUT an escalated request is not
            # an eviction — the retry supervisor prices it (the
            # spurious-75 rule's forcible sibling).
            return False
        count = int(
            entity.get(names.TASK_COL_EVICT_COUNT, 0) or 0) + 1
        try:
            self._merge_task(job_id, task_id, {
                "state": names.TASK_STATE_EVICTED,
                "node_id": None,
                names.TASK_COL_EVICTED_AT: now,
                names.TASK_COL_EVICT_COUNT: count,
                names.TASK_COL_PREEMPT_REQUEST: None,
                "not_before": None,
                "requeued_at": util.datetime_utcnow_iso(),
            }, if_match=entity["_etag"])
        except (EtagMismatchError, NotFoundError):
            return False
        goodput_events.emit(
            self.store, self.identity.pool_id,
            goodput_events.TASK_EVICTED, job_id=job_id,
            task_id=task_id, node_id=self.identity.node_id,
            attrs={"evict_count": count,
                   "reason": request.get("reason")},
            trace_id=entity.get(trace_context.COL_TRACE_ID),
            span_id=entity.get(trace_context.COL_TRACE_SPAN))
        # The burned notice window (notice -> hard-killed exit) on
        # the trace: how long the victim squatted past its notice.
        requested = goodput_events.iso_to_epoch(
            request.get("requested_at"))
        trace_spans.emit(
            self.store, self.identity.pool_id,
            trace_spans.SPAN_EVICT,
            trace_context.TraceContext.from_entity(entity),
            job_id=job_id, task_id=task_id,
            node_id=self.identity.node_id,
            start=(requested if requested and requested < now
                   else now),
            end=now,
            attrs={"evict_count": count,
                   "reason": request.get("reason")})
        queue = names.task_queue_for(
            self.identity.pool_id, task_id,
            self.pool.task_queue_shards,
            priority=int(spec.get("priority", 0) or 0))
        message = {"job_id": job_id, "task_id": task_id}
        if entity.get(trace_context.COL_TRACE_ID):
            message["trace_id"] = entity[trace_context.COL_TRACE_ID]
        if instances:
            self.store.put_messages(
                queue,
                [json.dumps({**message, "instance": k}).encode()
                 for k in range(instances)])
        else:
            self.store.put_message(queue,
                                   json.dumps(message).encode())
        logger.warning(
            "task %s/%s evicted (count %d); requeued at full retry "
            "budget — rerun resumes from the pre-notice COMMITTED "
            "barrier", job_id, task_id, count)
        return True

    def _elastic_size(self, spec: dict,
                      entity: dict) -> tuple[int, int]:
        """(current effective gang size, next attempt's size).

        Rigid gangs (no min_instances floor) never change size. An
        elastic gang's next attempt re-forms at whatever the pool can
        actually supply: max(min_instances, min(spec size, live
        nodes)) — shrinking when nodes were lost, growing back toward
        the spec size when capacity returned."""
        num_instances = spec["multi_instance"]["num_instances"]
        eff = int(entity.get(names.TASK_COL_GANG_SIZE)
                  or num_instances)
        min_inst = spec["multi_instance"].get("min_instances")
        if not min_inst or int(min_inst) >= num_instances:
            return eff, eff
        live = self._count_live_nodes()
        return eff, max(int(min_inst), min(num_instances, live))

    def _emit_gang_resize(self, job_id: str, task_id: str,
                          entity: dict, old_size: int,
                          new_size: int, attempt: int) -> None:
        goodput_events.emit(
            self.store, self.identity.pool_id,
            goodput_events.GANG_RESIZE, job_id=job_id,
            task_id=task_id,
            attrs={"old_size": old_size, "new_size": new_size,
                   "spec_size":
                       entity["spec"]["multi_instance"][
                           "num_instances"],
                   "attempt": attempt},
            trace_id=entity.get(trace_context.COL_TRACE_ID),
            span_id=entity.get(trace_context.COL_TRACE_SPAN))
        trace_spans.emit(
            self.store, self.identity.pool_id,
            trace_spans.SPAN_GANG_RESIZE,
            trace_context.TraceContext.from_entity(entity),
            job_id=job_id, task_id=task_id,
            node_id=self.identity.node_id,
            attrs={"old_size": old_size, "new_size": new_size,
                   "attempt": attempt})
        logger.warning(
            "gang %s/%s re-forming at size %d (was %d) for attempt "
            "%d", job_id, task_id, new_size, old_size, attempt)

    def _count_live_nodes(self) -> int:
        """Fresh, non-quarantined nodes of this pool — the capacity
        an elastic gang can actually re-form on (the _node_alive
        freshness rule, registration grace included)."""
        now = time.time()
        live = 0
        for node in self.store.query_entities(
                names.TABLE_NODES,
                partition_key=self.identity.pool_id):
            if node.get("state") in ("offline",):
                continue
            if node.get(names.NODE_COL_QUARANTINED):
                continue
            heartbeat = float(node.get("heartbeat_at", 0) or 0)
            if heartbeat > 0:
                fresh = now - heartbeat < self.node_stale_seconds
            else:
                registered = float(node.get("registered_at", 0) or 0)
                fresh = (registered > 0 and
                         now - registered < self.node_stale_seconds)
            if fresh:
                live += 1
        return live

    # ----------------------- compile-cache hooks -----------------------

    def _compile_cache_dir(self) -> str:
        """Node-local persistent compile cache, shared by every task
        on this node (exported as $SHIPYARD_COMPILE_CACHE_DIR)."""
        return os.path.join(self.work_dir, "compilecache")

    def _seed_compile_cache(self) -> None:
        """Pre-task seed: pull the pool's cache artifact so this task
        compiles warm (the image-prefetch pattern for executables).
        Generation-gated — an unchanged latest.json costs one
        metadata read, never a download. Best-effort by design."""
        try:
            meta = self.store.get_object_meta(
                names.compile_cache_latest_key(self.identity.pool_id))
        except NotFoundError:
            return
        except Exception:  # noqa: BLE001 - warm start is optional
            logger.debug("compile cache meta probe failed",
                         exc_info=True)
            return
        if meta.generation == self._compile_cache_seen_gen:
            return
        status = cc_seeding.seed_cache(
            self.store, self.identity.pool_id,
            self._compile_cache_dir())
        # Durable outcomes (seeded / refused-identity / already-warm)
        # latch on the artifact generation so an unchanged latest.json
        # is never re-downloaded; a TRANSIENT failure must not latch —
        # the next task retries, or one store hiccup would leave this
        # node cold until some other node publishes a newer artifact.
        if status != cc_seeding.ERROR:
            self._compile_cache_seen_gen = meta.generation

    def _export_compile_cache(self) -> None:
        """Post-task export: publish this node's cache subdirs as the
        pool seed (lease-guarded inside export_cache — one uploader
        per identity; nodes with nothing newer skip). Runs on a
        background thread: a first cold compile can leave a cache
        that takes real time to tar+upload, and that must not delay
        task finish accounting (the zero-stall lesson of the async
        checkpoint pipeline). No generation latch here — the export
        bumps latest.json, and the NEXT pre-task seed probe
        re-reads it: this node's own identities skip instantly on
        entry counts, while an identity another node published
        concurrently (whose records the export's read-modify-write
        may have folded in) still gets seeded rather than latched
        past."""
        thread = self._compile_cache_export_thread
        if thread is not None and thread.is_alive():
            return  # one in-flight export; the next finish retries

        def _run() -> None:
            cc_seeding.export_cache(
                self.store, self.identity.pool_id,
                self._compile_cache_dir(), self.identity.node_id)

        thread = threading.Thread(target=_run, daemon=True,
                                  name="compilecache-export")
        self._compile_cache_export_thread = thread
        thread.start()

    # ------------------ retry supervisor + node health -----------------

    def _backoff_seconds(self, task_id: str, retries: int) -> float:
        """Exponential backoff with deterministic jitter for attempt
        ``retries`` (1-based): base * 2^(n-1), capped, +-25% jitter
        keyed on (task, attempt) so a burst of simultaneous failures
        doesn't re-thunder onto the store in lockstep — and so chaos
        drills with a fixed seed replay the exact same schedule."""
        import zlib
        n = max(1, retries)
        delay = min(self.retry_backoff_cap,
                    self.retry_backoff_base * (2.0 ** (n - 1)))
        jitter = (zlib.crc32(f"{task_id}#{n}".encode()) % 1000) / 1000.0
        return delay * (0.75 + 0.5 * jitter)

    @staticmethod
    def _retry_decision(retries: int, max_retries: int) -> str:
        """THE supervisor policy, shared by the regular-task,
        gang-recovery, and gang-finalize paths: 'requeue' while the
        budget lasts (max_retries < 0 = unlimited), 'quarantine' once
        a configured budget is burned, 'fail' when no budget was ever
        configured (max_task_retries=0 keeps the legacy fail-fast
        contract)."""
        if max_retries < 0 or retries < max_retries:
            return "requeue"
        if max_retries > 0:
            return "quarantine"
        return "fail"

    def _append_attempt(self, entity: dict, exit_code: int,
                        reason: str) -> list[dict]:
        """Attempt-history entry for the quarantine diagnostics
        bundle, trimmed to the last 16 attempts."""
        history = list(entity.get("attempt_history") or [])
        history.append({"node_id": self.identity.node_id,
                        "exit_code": exit_code, "reason": reason,
                        "at": util.datetime_utcnow_iso()})
        return history[-16:]

    def _requeue_with_backoff(self, job_id: str, task_id: str,
                              spec: dict, retries: int,
                              exit_code: int, reason: str,
                              instances: Optional[int] = None,
                              if_match: Optional[str] = None,
                              extra: Optional[dict] = None) -> bool:
        """Retry supervisor requeue: bump the retry counter, stamp
        not_before (honored by the claim path; the queue message also
        carries the delay) and append the attempt to the diagnostics
        history. The backoff wait itself is priced by the claim side
        once it has elapsed (see _goodput_work_started). Returns
        False when the optimistic merge lost (someone else already
        transitioned the task)."""
        delay = self._backoff_seconds(task_id, retries)
        now = time.time()
        try:
            entity = self._task_entity(job_id, task_id)
        except NotFoundError:
            return False
        try:
            self._merge_task(job_id, task_id, {
                "state": "pending", "retries": retries,
                "last_exit_code": exit_code,
                "last_error": reason,
                "not_before": now + delay,
                "requeued_at": util.datetime_utcnow_iso(),
                "attempt_history": self._append_attempt(
                    entity, exit_code, reason),
                "node_id": None,
                # A pending preempt request dies with the attempt it
                # targeted: the failure requeue supersedes the drain
                # (the next sweep re-elects victims from live state).
                names.TASK_COL_PREEMPT_REQUEST: None,
                **(extra or {}),
            }, if_match=if_match)
        except (EtagMismatchError, NotFoundError):
            return False
        goodput_events.emit(
            self.store, self.identity.pool_id,
            goodput_events.TASK_RETRY, job_id=job_id,
            task_id=task_id, node_id=self.identity.node_id,
            attrs={"retries": retries, "exit_code": exit_code,
                   "reason": reason},
            trace_id=entity.get(trace_context.COL_TRACE_ID),
            span_id=entity.get(trace_context.COL_TRACE_SPAN))
        # Requeue marker on the trace: instantaneous, carrying the
        # supervisor's decision so the exported waterfall shows WHY
        # the next queue_wait span exists.
        trace_spans.emit(
            self.store, self.identity.pool_id,
            trace_spans.SPAN_REQUEUE,
            trace_context.TraceContext.from_entity(entity),
            job_id=job_id, task_id=task_id,
            node_id=self.identity.node_id,
            attrs={"retries": retries, "exit_code": exit_code,
                   "reason": reason, "backoff_seconds": delay})
        # The TASK_BACKOFF interval is emitted by the CLAIM side
        # (_goodput_work_started) once the wait has actually elapsed:
        # emitting [now, now+delay] here would future-date the event,
        # and any report or heimdall scrape taken during the window
        # would extend wall past the present and charge seconds that
        # never elapsed yet.
        queue = names.task_queue_for(
            self.identity.pool_id, task_id,
            self.pool.task_queue_shards,
            priority=int(spec.get("priority", 0) or 0))
        message = {"job_id": job_id, "task_id": task_id}
        if entity.get(trace_context.COL_TRACE_ID):
            message["trace_id"] = entity[trace_context.COL_TRACE_ID]
        if instances:
            self.store.put_messages(
                queue,
                [json.dumps({**message, "instance": k}).encode()
                 for k in range(instances)],
                delay_seconds=delay)
        else:
            self.store.put_message(
                queue, json.dumps(message).encode(),
                delay_seconds=delay)
        logger.warning(
            "task %s/%s requeued (attempt %d, %s); backoff %.1fs",
            job_id, task_id, retries, reason, delay)
        return True

    def _quarantine_task(self, job_id: str, task_id: str,
                         exit_code: int, reason: str,
                         stderr_path: Optional[str] = None,
                         if_match: Optional[str] = None) -> bool:
        """Poison quarantine: the task exhausted its retry budget.
        Park it terminally with a diagnostics bundle (last stderr
        tail, per-attempt node/exit history) so the operator reads
        the post-mortem off `jobs tasks list` instead of grepping
        node logs. Returns False when the merge lost."""
        tail = ""
        if stderr_path:
            try:
                with open(stderr_path, "rb") as fh:
                    fh.seek(max(0, os.path.getsize(stderr_path) - 2048))
                    tail = fh.read().decode(errors="replace")
            except OSError:
                pass
        try:
            entity = self._task_entity(job_id, task_id)
        except NotFoundError:
            return False
        history = self._append_attempt(entity, exit_code, reason)
        try:
            self._merge_task(job_id, task_id, {
                "state": names.TASK_STATE_QUARANTINED,
                "exit_code": exit_code,
                "error": f"retry budget exhausted: {reason}",
                "completed_at": util.datetime_utcnow_iso(),
                "node_id": None,
                # node/exit-code histories are projections of
                # attempt_history — derived at display time
                # (fleet.action_jobs_tasks_list), not stored thrice.
                "diagnostics": {
                    "stderr_tail": tail,
                    "attempt_history": history,
                },
            }, if_match=if_match)
        except (EtagMismatchError, NotFoundError):
            return False
        logger.error("task %s/%s quarantined after retry budget: %s",
                     job_id, task_id, reason)
        return True

    def _drop_live_proc(self, key: tuple[str, str],
                        mine: list) -> None:
        """Remove this run's proc from the live-proc registry — and
        ONLY this run's. A superseded gang zombie (its attempt was
        recovered while it ran) must not unregister the recovered
        attempt's proc on the same node, or term_task / chaos
        task_kill would silently miss the live rerun."""
        if mine and self._live_procs.get(key) is mine[-1]:
            self._live_procs.pop(key, None)

    def _run_task_registered(self, key: tuple[str, str],
                             execution: task_runner.TaskExecution,
                             ledger_slot: Optional[int] = None,
                             ledger_gang: bool = False,
                             ) -> task_runner.TaskResult:
        """run_task with live-proc registration (term_task control
        verbs and chaos task_kill/task_wedge target the proc through
        _live_procs), unregistering only its own entry on exit (see
        _drop_live_proc). Shared by the regular and gang paths.
        ``ledger_slot`` arms the crash-restart slot ledger: the
        launched pid is persisted so a restarted agent can re-adopt
        the still-running process instead of reclaim-rerunning it
        (the ledger is cleared by the completion path, not here — a
        crash between exit and classification must stay
        adoptable). ``ledger_gang`` marks the record as a gang
        member, which a restarted agent fences (kills) rather than
        adopts."""
        mine: list = []

        def _register(proc):
            mine.append(proc)
            self._live_procs[key] = proc
            if ledger_slot is not None:
                self._write_slot_ledger(ledger_slot, key, execution,
                                        proc, gang=ledger_gang)

        try:
            return task_runner.run_task(execution,
                                        on_start=_register)
        finally:
            self._drop_live_proc(key, mine)

    def _note_task_outcome(self, ok: bool,
                           wedged: bool = False,
                           neutral: bool = False) -> None:
        """Node health scoring: failures decay the score (wedges
        harder — a wedge usually implicates the node's accelerator
        state, not the task), successes recover it. Crossing the
        threshold quarantines the node: auto-drain via
        claim-exclusion (this agent stops claiming; observers read
        the column). Recovery back above the threshold un-drains.

        ``neutral=True`` skips scoring entirely: an EXTERNALLY-caused
        exit (cooperative preemption, chaos preempt notice) says
        nothing about this node's health — debiting it would let a
        burst of scheduler preemptions quarantine perfectly healthy
        nodes."""
        if neutral:
            return
        with self._health_lock:
            if ok:
                self._health = min(1.0, self._health + 0.1)
                self._recent_failures = max(
                    0, self._recent_failures - 1)
            elif wedged:
                self._health *= 0.5
                self._recent_failures += 1
            else:
                self._health *= 0.7
                self._recent_failures += 1
            was = self._node_quarantined
            self._node_quarantined = (
                self._health < self._health_quarantine_threshold)
            if self._node_quarantined and not was:
                self._quarantined_at = time.monotonic()
            health = self._health
            quarantined = self._node_quarantined
        if quarantined and not was:
            logger.error(
                "node %s health %.3f below threshold %.2f; "
                "quarantining (draining: no further claims)",
                self.identity.node_id, health,
                self._health_quarantine_threshold)
        elif was and not quarantined:
            logger.warning("node %s recovered (health %.3f); "
                           "resuming claims",
                           self.identity.node_id, health)
        # Advisory publish on the task-completion critical path: a
        # store hiccup here must not discard a finished task's result
        # (the periodic heartbeat now carries these columns, so a
        # lost publish self-repairs).
        try:
            self._heartbeat()
        except Exception:
            logger.exception("health publish failed; will ride the "
                             "next periodic heartbeat")

    def node_quarantined(self) -> bool:
        released = False
        with self._health_lock:
            if self._node_quarantined and (
                    time.monotonic() - self._quarantined_at
                    >= self._health_probation_seconds):
                # Probation lapsed: resume claims at exactly the
                # threshold score (see __init__ — quarantine must not
                # be a terminal state for the node).
                self._health = self._health_quarantine_threshold
                self._node_quarantined = False
                released = True
            health = self._health
            quarantined = self._node_quarantined
        if released:
            logger.warning(
                "node %s quarantine probation lapsed after %.0fs; "
                "resuming claims at health %.3f",
                self.identity.node_id,
                self._health_probation_seconds, health)
            try:
                self._heartbeat()
            except Exception:
                logger.exception("probation-release publish failed; "
                                 "will ride the next periodic "
                                 "heartbeat")
        return quarantined

    # ----------------------- regular task path -------------------------

    def _claim_regular(self, job_id: str, task_id: str,
                       entity: dict) -> Optional[str]:
        if entity.get("state") not in names.CLAIMABLE_TASK_STATES:
            return None
        if self.node_quarantined():
            return None
        try:
            # preempted_at/evicted_at are consumed here: the claim
            # closes the recovery intervals (_goodput_work_started
            # emits them from the pre-claim entity snapshot), and a
            # LATER failure-requeue of this attempt must not re-open
            # the old windows.
            return self._merge_task(
                job_id, task_id,
                {"state": "assigned",
                 "node_id": self.identity.node_id,
                 names.TASK_COL_PREEMPTED_AT: None,
                 names.TASK_COL_EVICTED_AT: None},
                if_match=entity["_etag"])
        except (EtagMismatchError, NotFoundError):
            return None

    def _run_regular_task(self, slot: int, job_id: str, task_id: str,
                          entity: dict, msg) -> None:
        if self._claim_regular(job_id, task_id, entity) is None:
            # Someone else claimed it; drop our copy of the message if
            # it is now terminal, else let visibility re-deliver.
            self.store.update_message(msg, visibility_timeout=10.0)
            return
        self._goodput_work_started(slot, job_id, task_id, entity)
        spec = entity["spec"]
        with self._message_keepalive(msg):
            if not self._ensure_job_prep(job_id, spec):
                self._merge_task(job_id, task_id, {
                    "state": "failed", "exit_code": -2,
                    "error": "job preparation failed on node "
                             f"{self.identity.node_id}"})
                self.store.delete_message(msg)
                self._maybe_autocomplete_job(job_id)
                self._goodput_work_done(slot)
                return
            try:
                self._ensure_images_timed(job_id, task_id, spec,
                                          entity=entity)
                execution = self._build_execution(slot, job_id,
                                                  task_id, spec,
                                                  entity=entity)
            except TaskEnvError as exc:
                self._merge_task(job_id, task_id, {
                    "state": "failed", "exit_code": -4,
                    "error": str(exc)})
                self.store.delete_message(msg)
                self._maybe_autocomplete_job(job_id)
                self._goodput_work_done(slot)
                return
            try:
                self._stage_inputs(spec, execution)
            except Exception as exc:
                logger.exception("input staging failed for %s/%s",
                                 job_id, task_id)
                self._merge_task(job_id, task_id, {
                    "state": "failed", "exit_code": -3,
                    "error": f"input staging failed: {exc}"})
                self.store.delete_message(msg)
                self._maybe_autocomplete_job(job_id)
                self._goodput_work_done(slot)
                return
            self._merge_task(job_id, task_id, {
                "state": "running",
                "started_at": util.datetime_utcnow_iso()})
            self._heartbeat(state="running")
            with self._running_lock:
                self._running_tasks += 1
            try:
                result = self._run_task_registered(
                    (job_id, task_id), execution, ledger_slot=slot)
            finally:
                with self._running_lock:
                    self._running_tasks -= 1
        if self._abandoned:
            # Simulated agent-process death (chaos agent_restart):
            # this thread is a zombie of the dead "process" — the
            # completion belongs to the restarted agent's adoption
            # watcher, which reads the slot ledger + exit-code
            # sentinel. A single store write here would
            # double-classify the exit.
            return
        self._finish_regular_result(slot, job_id, task_id, spec,
                                    entity, execution, result,
                                    msg=msg)

    def _finish_regular_result(self, slot: int, job_id: str,
                               task_id: str, spec: dict,
                               entity: dict,
                               execution: task_runner.TaskExecution,
                               result: task_runner.TaskResult,
                               msg=None) -> None:
        """Post-exit half of the regular-task path: uploads, goodput
        ingest, exit classification, requeue/quarantine/finish.
        Shared by the worker slot (msg = the claimed queue message)
        and the crash-restart adoption watcher (msg=None — the
        redelivered message dies on the terminal-state check once
        the entity goes terminal). Clears the slot ledger last: a
        crash anywhere before that leaves the task adoptable."""
        try:
            self._upload_outputs(job_id, task_id, execution)
        except Exception as exc:  # noqa: BLE001 - classify anyway
            # Classification must never be hostage to an upload: an
            # exception escaping here (store outage past the retry
            # ceiling, injected fault) would skip the exit handling
            # below and orphan-reclaim a FINISHED task into a rerun.
            # Lost stdout/stderr blobs are recorded and survivable;
            # a double execution is not.
            logger.exception("output upload failed for %s/%s",
                             job_id, task_id)
            try:
                self._merge_task(job_id, task_id,
                                 {"output_error": str(exc)})
            except Exception:  # noqa: BLE001 - best effort
                pass
        self._ingest_goodput(job_id, task_id, execution)
        self._upload_profile_artifacts(job_id, task_id, execution)
        self._export_compile_cache()
        self._goodput_task_finished(slot, job_id, task_id, result,
                                    entity=entity)
        try:
            self._collect_outputs(spec, execution, job_id, task_id)
        except Exception as exc:
            logger.exception("output collection failed for %s/%s",
                             job_id, task_id)
            self._merge_task(job_id, task_id,
                             {"output_error": str(exc)})
        try:
            ok = result.exit_code == 0
            # The distinct preempted status: a cooperative drain is a
            # scheduling transition, never a failure — full retry
            # budget, no node-health debit, no backoff.
            preempted = result.exit_code == preempt_mod.EXIT_PREEMPTED
            # The evicted status (the escalation ladder's hard kill):
            # we killed it ourselves (local marker), or the sweep's
            # escalation stamp is on the entity (cached read — covers
            # a restart between kill and classification). Externally
            # caused either way: never a wedge, never a node-health
            # debit.
            evicted = not ok and not preempted and (
                (job_id, task_id) in self._evicted_locally
                or self._escalated_request_pending(job_id, task_id))
            self._evicted_locally.discard((job_id, task_id))
            self._note_task_outcome(ok, wedged=result.wedged,
                                    neutral=preempted or evicted)
            retries = entity.get("retries", 0)
            max_retries = spec.get("max_task_retries", 0)
            reason = ("wedged: no progress beat within "
                      f"{spec.get('progress_deadline_seconds')}s"
                      if result.wedged else
                      f"exit code {result.exit_code}")
            decision = ("complete" if ok
                        else "preempted" if preempted
                        else "evicted" if evicted
                        else self._retry_decision(retries,
                                                  max_retries))
            if decision == "preempted":
                if self._requeue_preempted(job_id, task_id, spec):
                    self._heartbeat(state="idle")
                    self._ack_message(msg)
                    return
                decision = self._retry_decision(retries, max_retries)
            if decision == "evicted":
                if self._requeue_evicted(job_id, task_id, spec):
                    self._heartbeat(state="idle")
                    self._ack_message(msg)
                    return
                decision = self._retry_decision(retries, max_retries)
            if decision == "requeue":
                # Retry supervisor: exponential backoff + jitter, the
                # not_before stamp honored by every claimer.
                self._requeue_with_backoff(
                    job_id, task_id, spec, retries + 1,
                    result.exit_code, reason)
                self._heartbeat(state="idle")
                self._ack_message(msg)
                return
            if decision == "quarantine":
                # Poison quarantine: the budget is burned — park the
                # task with its post-mortem instead of plain "failed".
                if self._quarantine_task(
                        job_id, task_id, result.exit_code, reason,
                        stderr_path=result.stderr_path):
                    self._schedule_retention(spec, job_id, task_id)
                    self._heartbeat(state="idle")
                    self._ack_message(msg)
                    self._maybe_autocomplete_job(job_id)
                    return
            self._schedule_retention(spec, job_id, task_id)
            self._finish_task(job_id, task_id, result,
                              error=None if ok else reason)
            self._ack_message(msg)
            self._maybe_autocomplete_job(job_id)
        finally:
            self._clear_slot_ledger(slot, (job_id, task_id))

    def _ack_message(self, msg) -> None:
        """delete_message tolerant of the adoption path's msg=None
        (the watcher holds no queue message; redelivered copies die
        on the terminal-state check)."""
        if msg is not None:
            self.store.delete_message(msg)

    _RETENTION_MARKER = ".shipyard_retention_deadline"

    def _schedule_retention(self, spec: dict, job_id: str,
                            task_id: str) -> None:
        seconds = spec.get("retention_time_seconds")
        if seconds is None:
            return
        task_dir = os.path.join(self.work_dir, "tasks", job_id,
                                task_id)
        # Marker survives agent restarts: startup rescans for them so
        # pending sweeps are never orphaned (disk would otherwise
        # leak until the node dies).
        try:
            with open(os.path.join(task_dir, self._RETENTION_MARKER),
                      "w", encoding="utf-8") as fh:
                fh.write(str(time.time() + float(seconds)))
        except OSError:
            pass
        with self._retention_lock:
            self._retention.append(
                (time.monotonic() + float(seconds), task_dir))

    def _rescan_retention_markers(self) -> None:
        """Re-register sweeps recorded by a previous agent process
        (markers hold wall-clock deadlines)."""
        root = os.path.join(self.work_dir, "tasks")
        if not os.path.isdir(root):
            return
        now_wall = time.time()
        now_mono = time.monotonic()
        found = 0
        for job_id in os.listdir(root):
            job_dir = os.path.join(root, job_id)
            if not os.path.isdir(job_dir):
                continue
            for task_id in os.listdir(job_dir):
                marker = os.path.join(job_dir, task_id,
                                      self._RETENTION_MARKER)
                try:
                    with open(marker, encoding="utf-8") as fh:
                        wall_deadline = float(fh.read().strip())
                except (OSError, ValueError):
                    continue
                mono_deadline = now_mono + max(
                    0.0, wall_deadline - now_wall)
                with self._retention_lock:
                    self._retention.append(
                        (mono_deadline,
                         os.path.join(job_dir, task_id)))
                found += 1
        if found:
            logger.info("re-registered %d retention sweeps from "
                        "markers", found)

    # --------------------- crash-restart adoption ----------------------

    def _slot_ledger_path(self, slot: int) -> str:
        return os.path.join(self.work_dir, "slots",
                            f"slot{slot}.json")

    def _write_slot_ledger(self, slot: int, key: tuple[str, str],
                           execution: task_runner.TaskExecution,
                           proc, gang: bool = False) -> None:
        """Persist this slot's live claim (task identity, pid,
        container, the post-task env paths) at launch — the
        _atomic_write idiom (tmp + fsync + rename) so a crash
        mid-write can never surface a torn ledger. A restarted agent
        re-adopts from exactly this record instead of letting the
        janitor/orphan paths quarantine-rerun a task that never
        stopped running. ``gang`` marks a gang-member launch, whose
        restart handling is fence-by-kill rather than adoption (see
        _adopt_restart_state)."""
        pid = getattr(proc, "pid", None)
        record = {
            "slot": slot, "job_id": key[0], "task_id": key[1],
            "pid": pid,
            # Pid-identity anchor for _ledger_pid_matches: a pid the
            # OS recycled while the agent was down won't carry it.
            "pid_start_ticks": self._proc_start_ticks(pid),
            "runtime": execution.runtime,
            "container": task_runner.container_name(execution),
            "task_dir": execution.task_dir,
            "command": execution.command,
            # Only the framework's own path contract survives the
            # restart (goodput/trace sinks, profile dirs): resolved
            # user secrets must never touch the node's disk.
            "env": {k: v for k, v in execution.env.items()
                    if k.startswith("SHIPYARD_")},
            "started_at": util.datetime_utcnow_iso(),
        }
        path = self._slot_ledger_path(slot)
        try:
            os.makedirs(os.path.dirname(path), exist_ok=True)
            util.atomic_write(path,
                              json.dumps(record).encode("utf-8"))
        except OSError:
            logger.exception("slot ledger write failed for slot %d",
                             slot)

    def _clear_slot_ledger(self, slot: int,
                           key: Optional[tuple[str, str]] = None
                           ) -> None:
        """Retire a slot's ledger once its task is fully classified.
        ``key`` guards cross-task races: a ledger now naming a
        DIFFERENT task (the slot moved on) is someone else's."""
        path = self._slot_ledger_path(slot)
        if key is not None:
            try:
                with open(path, encoding="utf-8") as fh:
                    record = json.load(fh)
                if (record.get("job_id"),
                        record.get("task_id")) != key:
                    return
            except (OSError, ValueError):
                return
        try:
            os.remove(path)
        except OSError:
            pass

    @staticmethod
    def _pid_alive(pid: Optional[int]) -> bool:
        if not pid or pid <= 0:
            return False
        try:
            os.kill(pid, 0)
        except ProcessLookupError:
            return False
        except (PermissionError, OSError):
            return True
        return True

    @staticmethod
    def _proc_start_ticks(pid: Optional[int]) -> Optional[int]:
        """Kernel start time (clock ticks since boot) of ``pid`` from
        /proc — the cheap pid-identity disambiguator: a recycled pid
        never shares its predecessor's start tick. None off-Linux or
        once the process is gone."""
        if not pid or pid <= 0:
            return None
        try:
            with open(f"/proc/{pid}/stat", "rb") as fh:
                stat = fh.read().decode("ascii", "replace")
            # Field 22 (starttime); comm can embed spaces/parens, so
            # split only after the closing paren.
            return int(stat.rpartition(")")[2].split()[19])
        except (OSError, ValueError, IndexError):
            return None

    def _ledger_pid_matches(self, pid: Optional[int],
                            record: dict) -> bool:
        """Liveness AND identity of a ledgered pid: alive, still a
        session/group leader (every task launches with
        start_new_session, so pgid == pid), and carrying the same
        kernel start tick the ledger recorded at launch. An agent
        down long enough for the OS to recycle the number must not
        adopt-wait on — or worse, hard-kill — the stranger that
        inherited it."""
        if not self._pid_alive(pid):
            return False
        try:
            if os.getpgid(pid) != pid:
                return False
        except OSError:
            return False
        recorded = record.get("pid_start_ticks")
        current = self._proc_start_ticks(pid)
        if recorded is not None and current is not None \
                and recorded != current:
            return False
        return True

    @staticmethod
    def _read_adopted_exit(record: dict) -> Optional[int]:
        """The exit-code sentinel the task's own session (or the
        reaping runner) persisted — task_runner.EXIT_CODE_FILENAME
        in the ledgered task_dir. None while the task still runs (or
        when the outcome is truly unknown)."""
        task_dir = record.get("task_dir") or ""
        try:
            with open(os.path.join(
                    task_dir, task_runner.EXIT_CODE_FILENAME),
                    encoding="utf-8") as fh:
                return int(fh.read().strip())
        except (OSError, ValueError):
            return None

    def _adopt_restart_state(self) -> int:
        """Crash-restart adoption (start()-time): every slot ledger a
        previous agent process left behind is either ADOPTED — the
        claim is still ours and the process (or its exit-code
        sentinel) survives, so a watcher thread takes over the wait +
        completion path and the task finishes with retries untouched
        and neutral health — or retired, leaving the ordinary
        orphan-reclaim rerun semantics. The control-plane gap (last
        pre-crash heartbeat -> adoption) is priced as the `adoption`
        badput leg and traced as SPAN_AGENT_RESTART."""
        root = os.path.join(self.work_dir, "slots")
        if not os.path.isdir(root):
            return 0
        adopted = 0
        now = time.time()
        window_start = self._pre_restart_heartbeat
        if not window_start or window_start > now:
            window_start = now
        for name in sorted(os.listdir(root)):
            path = os.path.join(root, name)
            try:
                with open(path, encoding="utf-8") as fh:
                    record = json.load(fh)
                slot = int(record["slot"])
                job_id = record["job_id"]
                task_id = record["task_id"]
            except (OSError, ValueError, KeyError, TypeError):
                try:
                    os.remove(path)
                except OSError:
                    pass
                continue
            if record.get("gang"):
                # Gang members are FENCED, not adopted: the
                # rendezvous context this launch belonged to (member
                # list, gang env, the i{instance} row merge and gang
                # finalize) died with the old agent process, so no
                # watcher could classify the exit honestly — and the
                # gang recovery paths (orphaned-gang janitor,
                # requeue-as-a-unit) already own the rerun. What must
                # NOT survive is the process itself: a live leftover
                # member writing into the task dir while the requeued
                # gang re-runs is exactly the double execution the
                # ledger exists to prevent. No store read needed —
                # fencing is purely local, so it works with the store
                # dark at boot.
                pid = record.get("pid")
                if self._ledger_pid_matches(pid, record) and \
                        self._read_adopted_exit(record) is None:
                    logger.warning(
                        "fencing leftover gang member %s/%s (pid %s) "
                        "after agent restart", job_id, task_id, pid)
                    self._hard_kill_task_group(job_id, task_id, pid)
                self._clear_slot_ledger(slot)
                continue
            try:
                # Bounded like the heartbeat probe above: boot must
                # not block max_outage_seconds per ledger when the
                # store is dark.
                with self._store_bounded(
                        max(10.0, 2.0 * self.heartbeat_interval)):
                    entity = self._task_entity(job_id, task_id)
            except NotFoundError:
                entity = None
            except Exception:  # noqa: BLE001 - store down at boot
                logger.debug("adoption probe failed", exc_info=True)
                continue  # ledger kept: retry next restart
            if (entity is None
                    or entity.get("node_id") != self.identity.node_id
                    or entity.get("state") not in ("assigned",
                                                   "running")):
                # The world moved on (terminal, re-owned, gone):
                # nothing left to adopt. A leftover process that IS
                # still ours-by-ledger gets fenced first — the claim
                # it served no longer exists, and the rerun that
                # replaced it must never share output dirs with a
                # live predecessor.
                pid = record.get("pid")
                if self._ledger_pid_matches(pid, record) and \
                        self._read_adopted_exit(record) is None:
                    logger.warning(
                        "fencing leftover process for re-owned task "
                        "%s/%s (pid %s) after agent restart",
                        job_id, task_id, pid)
                    self._hard_kill_task_group(job_id, task_id, pid)
                self._clear_slot_ledger(slot)
                continue
            pid = record.get("pid")
            alive = self._ledger_pid_matches(pid, record)
            exit_code = self._read_adopted_exit(record)
            if not alive and exit_code is None:
                # Process gone AND outcome unknown: adoption cannot
                # classify honestly — leave the rerun to the
                # orphan-reclaim path (retries budgeted, as today).
                self._clear_slot_ledger(slot)
                continue
            proc = _AdoptedProc(pid)
            self._adopted_slots.add(slot)
            # Register EVERY adoption (dead-pid ones included): the
            # registration is what makes _maybe_reclaim_orphan back
            # off a redelivered message on a SIBLING slot — without
            # it, a dead-pid adoption races its own reclaim-rerun
            # into a double execution. Kill-path consumers tolerate
            # a dead pid (ProcessLookupError handled everywhere).
            self._live_procs[(job_id, task_id)] = proc
            ctx = trace_context.TraceContext.from_entity(entity)
            goodput_events.emit(
                self.store, self.identity.pool_id,
                goodput_events.TASK_ADOPTION, job_id=job_id,
                task_id=task_id, node_id=self.identity.node_id,
                start=window_start, end=now,
                attrs={"pid": pid, "proc_alive": alive,
                       "retries": entity.get("retries", 0)},
                trace_id=entity.get(trace_context.COL_TRACE_ID),
                span_id=entity.get(trace_context.COL_TRACE_SPAN))
            trace_spans.emit(
                self.store, self.identity.pool_id,
                trace_spans.SPAN_AGENT_RESTART, ctx, job_id=job_id,
                task_id=task_id, node_id=self.identity.node_id,
                start=window_start, end=now,
                attrs={"pid": pid, "proc_alive": alive})
            thread = threading.Thread(
                target=self._adopt_watch,
                args=(record, entity, proc, alive),
                name=(f"adopt-{self.identity.node_id}"
                      f"-s{slot}"), daemon=True)
            thread.start()
            self._threads.append(thread)
            adopted += 1
            logger.warning(
                "adopted %s task %s/%s on slot %d after agent "
                "restart (pid %s)",
                "running" if alive else "exited", job_id, task_id,
                slot, pid)
        return adopted

    def _adopt_watch(self, record: dict, entity: dict, proc,
                     was_alive: bool) -> None:
        """Adoption watcher: stand in for the dead worker slot's
        blocking wait — poll the adopted pid, read the exit-code
        sentinel its session persisted, and drive the SAME
        completion path a live slot would have (uploads, goodput,
        classification). The task's retry budget is untouched by
        construction: no requeue ever happened."""
        slot = int(record["slot"])
        job_id, task_id = record["job_id"], record["task_id"]
        key = (job_id, task_id)
        counted = False
        # Adoption must not shed the task's runtime limits: the
        # original run_task watchdog died with the old agent, so THIS
        # loop re-arms wall-time (elapsed since the original launch)
        # and the progress watchdog (the beat file's mtime survives
        # the restart). Without them a wedged adopted task would hold
        # its slot forever — the exact hang class the watchdog
        # exists to bound.
        spec = entity.get("spec") or {}
        wall_limit = spec.get("max_wall_time_seconds")
        watchdog = spec.get("progress_deadline_seconds")
        progress_file = (record.get("env") or {}).get(
            progress_mod.PROGRESS_FILE_ENV)
        started_epoch = goodput_events.iso_to_epoch(
            record.get("started_at"))
        adopted_at = time.time()
        timed_out = False
        wedged = False
        try:
            with self._running_lock:
                self._goodput_idle_since = None
                self._goodput_busy_slots.add(slot)
                if was_alive:
                    self._running_tasks += 1
                    counted = True
            while self._ledger_pid_matches(proc.pid, record) and \
                    not self.stop_event.is_set():
                # The sentinel outranks pid liveness: once the task's
                # own session wrote its exit code, the command IS
                # done. Liveness itself is the full identity check
                # (_ledger_pid_matches, not _pid_alive): a pid the OS
                # recycles MID-WATCH would otherwise strand this
                # watcher "running" forever — or worse, hand the
                # wall/wedge enforcement below a stranger's process
                # group to hard-kill.
                if self._read_adopted_exit(record) is not None:
                    break
                now = time.time()
                elapsed = now - (started_epoch or adopted_at)
                if wall_limit is not None and elapsed > wall_limit:
                    timed_out = True
                    logger.warning(
                        "adopted task %s/%s exceeded wall time "
                        "%.1fs; killing", job_id, task_id,
                        float(wall_limit))
                    self._hard_kill_task_group(job_id, task_id,
                                             proc.pid)
                    break
                if watchdog is not None and progress_file:
                    beat = progress_mod.last_beat(progress_file)
                    # A missing beat file restarts the clock at
                    # adoption (conservative: the full deadline
                    # again, never a false wedge from lost state).
                    stale = (now - beat if beat is not None
                             else now - adopted_at)
                    if stale > watchdog:
                        wedged = True
                        logger.warning(
                            "adopted task %s/%s made no progress "
                            "for %.1fs (deadline %.1fs); killing as "
                            "wedged", job_id, task_id, stale,
                            float(watchdog))
                        self._hard_kill_task_group(job_id, task_id,
                                                 proc.pid)
                        break
                time.sleep(max(0.05, min(0.25, self.poll_interval)))
            if self.stop_event.is_set() and \
                    self._ledger_pid_matches(proc.pid, record) and \
                    self._read_adopted_exit(record) is None:
                # Stopping again mid-adoption: the ledger stays — the
                # NEXT restart adopts the still-running task.
                return
            if timed_out or wedged:
                # Our own kill: the classification is known — no
                # sentinel will appear (SIGKILL never runs the
                # trailer) and a handback would erase a genuine
                # wall/wedge verdict.
                exit_code = -9
            else:
                # The sentinel can lag the pid death by the shell
                # trailer's mv; poll briefly.
                exit_code = None
                deadline = time.monotonic() + 5.0
                while exit_code is None and \
                        time.monotonic() < deadline:
                    exit_code = self._read_adopted_exit(record)
                    if exit_code is None:
                        time.sleep(0.05)
                if exit_code is None and record.get("container"):
                    # Containerized task: only the shell trailer of
                    # runtime "none" writes the sentinel from inside
                    # the task's session, so ask the runtime itself.
                    exit_code = self._container_exit_code(
                        record["container"])
                if exit_code is None:
                    if record.get("runtime", "none") == "none":
                        # The trailer writes the sentinel on ANY
                        # normal exit; its absence means the session
                        # was hard-killed externally — classify as
                        # the kill it was; the retry supervisor
                        # prices the rerun.
                        exit_code = -9
                    else:
                        # Containerized outcome genuinely unknowable
                        # (e.g. --rm removed the container before we
                        # could ask). Never guess a FAILURE for a
                        # task that may have succeeded: hand it back
                        # through the orphan-reclaim semantics —
                        # reset pending, no retry consumed, no
                        # health debit.
                        self._abandon_adoption_to_reclaim(
                            job_id, task_id, slot)
                        return
            task_dir = record.get("task_dir") or os.path.join(
                self.work_dir, "tasks", job_id, task_id)
            execution = task_runner.TaskExecution(
                pool_id=self.identity.pool_id, job_id=job_id,
                task_id=task_id, node_id=self.identity.node_id,
                node_index=self.identity.node_index,
                command=record.get("command", ""),
                runtime=record.get("runtime", "none"),
                env=dict(record.get("env") or {}),
                task_dir=task_dir, slot=slot,
                record_exit_code=True)
            started_at = record.get("started_at") or \
                util.datetime_utcnow_iso()
            started = goodput_events.iso_to_epoch(started_at) or \
                time.time()
            result = task_runner.TaskResult(
                exit_code=exit_code,
                stdout_path=os.path.join(task_dir, "stdout.txt"),
                stderr_path=os.path.join(task_dir, "stderr.txt"),
                started_at=started_at,
                completed_at=util.datetime_utcnow_iso(),
                wall_seconds=max(0.0, time.time() - started),
                timed_out=timed_out, wedged=wedged)
            try:
                fresh = self._task_entity(job_id, task_id)
            except Exception:  # noqa: BLE001 - keep the snapshot
                fresh = entity
            self._finish_regular_result(
                slot, job_id, task_id, fresh.get("spec") or {},
                fresh, execution, result, msg=None)
        except Exception:
            logger.exception("adoption watcher failed for %s/%s",
                             job_id, task_id)
        finally:
            if counted:
                with self._running_lock:
                    self._running_tasks -= 1
            if self._live_procs.get(key) is proc:
                self._live_procs.pop(key, None)
            self._goodput_work_done(slot)
            self._adopted_slots.discard(slot)

    @staticmethod
    def _hard_kill_task_group(job_id: str, task_id: str,
                              pid: int) -> None:
        """Hard-kill a task's live process group on THIS node
        (eviction enforcement, adopted-task wall/wedge enforcement):
        docker containers force-removed first — SIGKILL is never
        proxied by the docker client (the task_runner wedge lesson;
        fixed-name convention from task_runner.container_name, one
        rm -f per possible instance container) — then the session
        group eats SIGKILL (tasks launch with start_new_session, so
        pgid == pid)."""
        import shutil as shutil_mod
        import signal as signal_mod
        import subprocess as subprocess_mod
        if shutil_mod.which("docker"):
            rc, out, _err = util.subprocess_capture(
                ["docker", "ps", "--filter",
                 f"name=shipyard-{job_id}-{task_id}-",
                 "--format", "{{.Names}}"])
            for name in (out.split() if rc == 0 else []):
                subprocess_mod.call(
                    ["docker", "rm", "-f", name],
                    stdout=subprocess_mod.DEVNULL,
                    stderr=subprocess_mod.DEVNULL)
        try:
            os.killpg(os.getpgid(pid), signal_mod.SIGKILL)
        except (ProcessLookupError, PermissionError, OSError):
            pass

    @staticmethod
    def _container_exit_code(container: str) -> Optional[int]:
        """The runtime's own record of a finished container's exit
        code (`docker inspect`); None when docker is absent or the
        container is gone (e.g. --rm already removed it)."""
        import shutil as shutil_mod
        if not shutil_mod.which("docker"):
            return None
        rc, out, _err = util.subprocess_capture(
            ["docker", "inspect", "-f", "{{.State.ExitCode}}",
             container])
        if rc != 0:
            return None
        try:
            return int(out.strip())
        except ValueError:
            return None

    def _abandon_adoption_to_reclaim(self, job_id: str,
                                     task_id: str,
                                     slot: int) -> None:
        """Unknown-outcome adoption exit: reset the claim exactly
        like the orphan-reclaim path would (pending, no retry bump,
        requeued_at restarts the queue clock) so the rerun costs
        repeat work but never budget or health."""
        logger.warning(
            "adopted task %s/%s finished with an unknowable exit; "
            "handing back to the reclaim path", job_id, task_id)
        try:
            entity = self._task_entity(job_id, task_id)
            if entity.get("state") in ("assigned", "running") and \
                    entity.get("node_id") == self.identity.node_id:
                self._merge_task(
                    job_id, task_id,
                    {"state": "pending", "node_id": None,
                     "requeued_at": util.datetime_utcnow_iso()},
                    if_match=entity["_etag"])
        except (EtagMismatchError, NotFoundError):
            pass
        except Exception:  # noqa: BLE001 - orphan reclaim retries
            logger.exception("adoption handback failed for %s/%s",
                             job_id, task_id)
        self._clear_slot_ledger(slot, (job_id, task_id))

    def _sweep_retention(self) -> None:
        now = time.monotonic()
        expired: list[str] = []
        with self._retention_lock:
            keep: list[tuple[float, str]] = []
            for deadline, task_dir in self._retention:
                if deadline <= now:
                    expired.append(task_dir)
                else:
                    keep.append((deadline, task_dir))
            self._retention = keep
        if expired:
            import shutil as shutil_mod
            for task_dir in expired:
                shutil_mod.rmtree(task_dir, ignore_errors=True)
                logger.info("retention expired; removed %s", task_dir)

    def _finish_task(self, job_id: str, task_id: str,
                     result: task_runner.TaskResult,
                     error: Optional[str] = None) -> None:
        patch = {
            "state": "completed" if result.exit_code == 0 else "failed",
            "exit_code": result.exit_code,
            "timed_out": result.timed_out,
            "wedged": result.wedged,
            "completed_at": result.completed_at,
            "wall_seconds": result.wall_seconds,
        }
        if error:
            patch["error"] = error
        self._merge_task(job_id, task_id, patch)
        self._heartbeat(state="idle")

    # ------------------------ gang (MI) task path ----------------------

    @staticmethod
    def _gang_attempt(entity: dict) -> int:
        """Rendezvous attempt index: retries + preempt_count +
        evict_count. A preempted/evicted requeue keeps the retry
        budget untouched but must STILL re-form in a fresh partition
        — reusing the drained attempt's partition would race its row
        cleanup against the rerun's claims (a fast claimer could
        insert rows the finalizer's clear then deletes, wedging the
        rendezvous)."""
        return (int(entity.get("retries", 0) or 0)
                + int(entity.get(names.TASK_COL_PREEMPT_COUNT, 0)
                      or 0)
                + int(entity.get(names.TASK_COL_EVICT_COUNT, 0)
                      or 0))

    def _gang_pk(self, job_id: str, task_id: str,
                 entity: dict) -> str:
        """Attempt-namespaced gang partition: each recovery attempt —
        retry OR preemption — rendezvouses in a fresh partition, so a
        zombie member of a recovered gang can never corrupt the
        rerun's rows (see names.gang_pk)."""
        return names.gang_pk(self.identity.pool_id, job_id, task_id,
                             attempt=self._gang_attempt(entity))

    def _gang_claim(self, gang_pk: str, instance: int) -> bool:
        """Claim gang instance k for this node. One instance per node:
        a second claim by the same node is released and requeued.
        Quarantined nodes never join a gang — one sick participant
        wedges the whole ICI collective.

        A True return registers the claim in _active_gang_claims;
        the caller must release it on exit (_run_gang_instance's
        finally)."""
        if self.node_quarantined():
            return False
        try:
            self.store.insert_entity(
                names.TABLE_GANGS, gang_pk, f"node${self.identity.node_id}",
                {"instance": instance})
        except EntityExistsError:
            # Our marker already exists: either another slot of this
            # node is live in this gang (bounce), or a crashed slot
            # abandoned its claim (resume).
            return self._resume_own_gang_claim(gang_pk, instance)
        try:
            self.store.insert_entity(
                names.TABLE_GANGS, gang_pk, f"i{instance}", {
                    "node_id": self.identity.node_id,
                    "hostname": self.identity.hostname,
                    "internal_ip": self.identity.internal_ip,
                    "slice_index": self.identity.slice_index,
                    "worker_index": self.identity.worker_index,
                    "state": "joined",
                })
            with self._running_lock:
                self._active_gang_claims.add((gang_pk, instance))
            return True
        except EntityExistsError:
            # Our own instance row with the marker missing (a partial
            # crash undid the marker but leaked the row): resume it,
            # keeping the marker just re-inserted.
            if self._resume_own_gang_claim(gang_pk, instance):
                return True
            # Instance already claimed elsewhere; undo node marker.
            self.store.delete_entity(
                names.TABLE_GANGS, gang_pk,
                f"node${self.identity.node_id}")
            return False
        except Exception:
            # Store fault between the two inserts: without the undo
            # the marker leaks (orphaned gang row) and this node is
            # locked out of the attempt partition forever.
            try:
                self.store.delete_entity(
                    names.TABLE_GANGS, gang_pk,
                    f"node${self.identity.node_id}")
            except Exception:
                logger.exception("gang claim undo failed for %s "
                                 "(terminal sweep will retire it)",
                                 gang_pk)
            raise

    def _resume_own_gang_claim(self, gang_pk: str,
                               instance: int) -> bool:
        """Take back this node's own ABANDONED claim: the instance
        row is ours and still 'joined', but no worker slot here holds
        it live — a store fault after _gang_claim crashed the slot
        out of the rendezvous. The node stays alive, so no gang
        observer will ever judge the row stale, and no other node can
        insert over it: without resume the gang wedges forever.
        Registers the claim atomically with the liveness check so a
        duplicate message copy in another slot cannot double-run."""
        try:
            row = self.store.get_entity(
                names.TABLE_GANGS, gang_pk, f"i{instance}")
        except NotFoundError:
            return False
        if (row.get("node_id") != self.identity.node_id
                or row.get("state") != "joined"):
            return False
        with self._running_lock:
            if (gang_pk, instance) in self._active_gang_claims:
                return False
            self._active_gang_claims.add((gang_pk, instance))
        logger.warning(
            "resuming abandoned gang claim %s i%d (a prior worker "
            "slot crashed out of the rendezvous)", gang_pk, instance)
        return True

    def _gang_members(self, gang_pk: str) -> list[dict]:
        return [e for e in self.store.query_entities(
            names.TABLE_GANGS, partition_key=gang_pk, row_key_prefix="i")]

    def _node_alive(self, node_id: str) -> bool:
        """THE liveness predicate (shared by orphan reclaim and gang
        health): node entity present, not offline, heartbeat fresh.

        Registration grace: a node entity exists from the moment the
        substrate registers it, but its FIRST heartbeat only lands
        once the agent boots — judging heartbeat_at=0 as "dead" let a
        gang observer fail a healthy just-booted member (the startup
        race). A node that has never heartbeated is alive while its
        registration is younger than the staleness window."""
        try:
            node = self.store.get_entity(
                names.TABLE_NODES, self.identity.pool_id, node_id)
        except NotFoundError:
            return False
        if node.get("state") in ("offline",):
            return False
        heartbeat = float(node.get("heartbeat_at", 0) or 0)
        if heartbeat <= 0:
            registered = float(node.get("registered_at", 0) or 0)
            return (registered > 0 and
                    time.time() - registered < self.node_stale_seconds)
        return time.time() - heartbeat < self.node_stale_seconds

    def _stale_gang_members(self, members: list[dict]) -> list[dict]:
        """Joined (not yet done) members whose node died — a
        crashed/preempted gang participant. A broken gang cannot
        produce a correct collective result; the observer fails the
        task fast instead of letting the rendezvous (or the job) hang.
        Critical for gangs on preemptible TPU slices."""
        stale = []
        for member in members:
            if member.get("state") == "done":
                continue
            node_id = member.get("node_id")
            if node_id == self.identity.node_id:
                continue
            if not self._node_alive(node_id):
                stale.append(member)
        return stale

    def _clear_gang_rows(self, gang_pk: str) -> None:
        for row in list(self.store.query_entities(
                names.TABLE_GANGS, partition_key=gang_pk)):
            try:
                self.store.delete_entity(names.TABLE_GANGS, gang_pk,
                                         row["_rk"])
            except NotFoundError:
                pass

    def _sweep_task_expansions(self) -> None:
        """Leader-gated pickup of parked server-side task-factory
        expansions (jobs/expansion.py). The sweep itself only looks —
        one partition query to learn whether any row owes work — then
        spawns at most one dedicated expander thread for the slow
        materialization: a 10^6-task expansion runs for minutes and
        must never ride the heartbeat thread. Every chunk the thread
        commits is fenced on this term's epoch, so a deposed leader's
        in-flight expander goes inert instead of double-writing."""
        if (time.monotonic() - self._last_expansion_sweep
                < self.expansion_sweep_interval):
            return
        self._last_expansion_sweep = time.monotonic()
        thread = self._expander_thread
        if thread is not None and thread.is_alive():
            return  # the running expander drains pending rows itself
        # Look BEFORE leading: the pending probe is one tiny
        # partition query, and taking the lease first would keep the
        # whole pool churning expander terms forever after the last
        # expansion completes. Pending rows only ever appear via
        # `jobs add`, so a pre-lease probe can't miss work for longer
        # than one sweep interval.
        from batch_shipyard_tpu.jobs import expansion as expansion_mod
        if not expansion_mod.pending_expansions(
                self.store, self.identity.pool_id):
            return
        epoch = self._sweep_leader_epoch(state_leases.ROLE_EXPANDER)
        if epoch is None:
            return
        lease = self._sweep_lease(state_leases.ROLE_EXPANDER)

        def _run() -> None:
            try:
                expansion_mod.run_pending_expansions(
                    self.store, self.identity.pool_id,
                    node_id=self.identity.node_id,
                    fenced=lambda: lease.fenced(epoch),
                    stop_check=self.stop_event.is_set)
            except Exception:
                logger.exception("task expansion run failed")

        thread = threading.Thread(
            target=_run,
            name=f"expander-{self.identity.node_id}", daemon=True)
        self._expander_thread = thread
        thread.start()

    def _sweep_lease(self, role: str) -> state_leases.LeaderLease:
        """The named leadership lease of one leader-gated loop,
        created lazily so a node whose sweep never runs (disabled
        preempt interval) never competes for its lease."""
        lease = self._sweep_leases.get(role)
        if lease is None:
            lease = state_leases.LeaderLease(
                self.store,
                key=names.leader_lease_key(self.identity.pool_id,
                                           role),
                epoch_key=names.leader_epoch_key(
                    self.identity.pool_id, role),
                owner=self.identity.node_id,
                duration_seconds=self.leader_lease_seconds,
                blocked=lambda: (time.time()
                                 < self.lease_blackout_until))
            self._sweep_leases[role] = lease
        return lease

    def _sweep_leader_epoch(self, role: str) -> Optional[int]:
        """Leadership gate for leader-gated sweeps: the current
        term's fencing epoch while THIS node holds the role's lease,
        None otherwise. Replaces the old heartbeat-freshness election
        (`_is_gang_sweep_leader`): a lease can only be extended
        through the store, so a partitioned leader abdicates on its
        own clock strictly before a successor can acquire — there is
        no double-leader window — and the epoch fences every sweep
        write a deposed leader might still have in flight."""
        try:
            return self._sweep_lease(role).epoch()
        except Exception:  # noqa: BLE001 - store hiccup = not leader
            logger.debug("sweep lease check failed for %s", role,
                         exc_info=True)
            return None

    def _store_bounded(self, seconds: float):
        """Bounded critical-retry window when the store wrapper
        supports it (state/resilient.py ``bounded``); an identity
        context on a bare store, where transport errors surface
        immediately anyway."""
        bounded = getattr(self.store, "bounded", None)
        if callable(bounded):
            return bounded(seconds)
        return contextlib.nullcontext()

    def _renew_sweep_leases(self) -> None:
        """Heartbeat-cadence renewal of HELD sweep leases (sweep
        intervals can exceed the lease duration; the heartbeat is
        the keepalive). Renew-only: acquisition belongs to the gated
        loops themselves."""
        for lease in self._sweep_leases.values():
            try:
                lease.maintain()
            except Exception:  # noqa: BLE001 - heartbeat survives
                logger.debug("sweep lease renew failed",
                             exc_info=True)

    def _sweep_orphaned_gangs(self) -> None:
        """Janitor for leaked rendezvous rows: a gang cleanup
        interrupted mid-flight (store fault between a task's state
        transition and its row clear, or a claim whose second insert
        failed) is never retried by the member that owed it — the
        rows would outlive their task forever. Any partition whose
        task is terminal, gone, or already past that attempt
        (entity retries advanced) is garbage. Clearing is
        idempotent, so concurrent sweepers on other nodes are
        harmless."""
        if (time.monotonic() - self._last_gang_sweep
                < self.gang_sweep_interval):
            return
        self._last_gang_sweep = time.monotonic()
        # One sweeper per pool: the table scan below is unpartitioned
        # (no prefix query in the store interface), so N nodes each
        # scanning every interval would multiply fleet-wide read
        # traffic for zero extra safety. The janitor lease elects
        # exactly one sweeper per term (state/leases.py) — no
        # failover window at all, unlike the old heartbeat-freshness
        # election.
        epoch = self._sweep_leader_epoch(
            state_leases.ROLE_GANG_JANITOR)
        if epoch is None:
            return
        prefix = f"{self.identity.pool_id}$"
        seen: set[str] = set()
        lease = self._sweep_lease(state_leases.ROLE_GANG_JANITOR)
        for row in list(self.store.query_entities(names.TABLE_GANGS)):
            pk = row["_pk"]
            if pk in seen or not pk.startswith(prefix):
                continue
            seen.add(pk)
            base, _, suffix = pk.partition("#g")
            try:
                attempt = int(suffix) if suffix else 0
            except ValueError:
                continue
            parts = base.split("$")
            if len(parts) != 3:
                continue
            _, job_id, task_id = parts
            try:
                entity = self._task_entity(job_id, task_id)
            except NotFoundError:
                entity = None
            if (entity is not None
                    and entity.get("state")
                    not in names.TERMINAL_TASK_STATES
                    and self._gang_attempt(entity) <= attempt):
                # Live (or future) rendezvous attempt — not garbage.
                continue
            # Fencing re-check BEFORE the write: the scan above can
            # outlive the term (satellite audit — the verdict cached
            # at the top of the loop must not authorize a stale
            # clear). Clearing is idempotent, so this only bounds
            # the deposed leader's wasted work, but the discipline
            # is uniform across every fenced sweep.
            if not lease.fenced(epoch):
                return
            logger.warning("sweeping orphaned gang rows in %s", pk)
            self._clear_gang_rows(pk)

    def _clear_gang_history(self, job_id: str, task_id: str,
                            attempts: int) -> None:
        """Retire EVERY attempt's rendezvous partition once the task
        is terminal. An earlier attempt can leak rows when its
        cleanup was cut short mid-flight (a store fault between the
        requeue transition and its clear, or a claim whose second
        insert failed): nothing retries those clears, so the
        terminal transition sweeps attempts 0..attempts (the combined
        retries+preempt_count index, _gang_attempt) to self-repair.
        Best-effort per partition — a fault here leaves at most what
        was already leaked."""
        for attempt in range(attempts + 1):
            pk = names.gang_pk(self.identity.pool_id, job_id,
                               task_id, attempt=attempt)
            try:
                self._clear_gang_rows(pk)
            except Exception:
                logger.exception("gang row sweep failed for %s", pk)

    def _recover_broken_gang(self, job_id: str, task_id: str,
                             gang_pk: str, stale: list[dict],
                             msg, attempt: int = 0) -> None:
        """Checkpoint-aware gang requeue: a gang that lost a member
        (preemption, crash, wedge-killed node) is RE-RUN from its
        latest COMMITTED checkpoint instead of failed terminally —
        within the retry budget the whole gang requeues with backoff
        (the rerun's restore pulls the committed step, so only the
        steps since that checkpoint are rework: exactly the
        preemption_recovery badput the goodput engine prices).
        Exhausting the budget quarantines the task with diagnostics.

        Every surviving member observes the same breakage; the
        etag-guarded requeue/quarantine merge elects one recoverer —
        losers only drop their message."""
        dead = sorted({m.get("node_id", "?") for m in stale})
        try:
            entity = self._task_entity(job_id, task_id)
        except NotFoundError:
            self.store.delete_message(msg)
            return
        retries = int(entity.get("retries", 0))
        if entity.get("state") in names.TERMINAL_TASK_STATES or \
                self._gang_attempt(entity) != attempt:
            # Terminally resolved, or a peer already recovered this
            # attempt (every recovery bumps the combined attempt
            # index — state alone can't discriminate: a gang broken
            # during FORMATION is still legitimately "pending").
            self.store.delete_message(msg)
            return
        spec = entity["spec"]
        max_retries = spec.get("max_task_retries", 0)
        num_instances = spec["multi_instance"]["num_instances"]
        # Elastic resize: the rerun re-forms at whatever the pool can
        # actually supply — shrinking when nodes were lost, growing
        # back toward the spec size when capacity returned. The
        # rerun's restore re-shards the committed checkpoint onto the
        # new mesh (parallel/sharding.reshard_on_restore).
        eff_size, new_size = self._elastic_size(spec, entity)
        reason = f"gang member(s) lost: {dead}"
        decision = self._retry_decision(retries, max_retries)
        logger.warning("gang %s/%s lost member(s) %s; %s",
                       job_id, task_id, dead,
                       (f"requeuing at size {new_size} from "
                        f"committed checkpoint")
                       if decision == "requeue"
                       else "retry budget exhausted")
        if decision == "requeue":
            if self._requeue_with_backoff(
                    job_id, task_id, spec, retries + 1, -4, reason,
                    instances=new_size,
                    if_match=entity["_etag"],
                    extra={names.TASK_COL_GANG_SIZE:
                           new_size if new_size != num_instances
                           else None}):
                goodput_events.emit(
                    self.store, self.identity.pool_id,
                    goodput_events.NODE_PREEMPTED, job_id=job_id,
                    task_id=task_id,
                    attrs={"dead_nodes": dead, "gang": True})
                if new_size != eff_size:
                    self._emit_gang_resize(job_id, task_id, entity,
                                           eff_size, new_size,
                                           retries + 1)
                self._clear_gang_rows(gang_pk)
        elif decision == "quarantine":
            # A configured budget got burned: poison quarantine with
            # the diagnostics bundle.
            if self._quarantine_task(job_id, task_id, -4, reason,
                                     if_match=entity["_etag"]):
                self._clear_gang_history(job_id, task_id,
                                         self._gang_attempt(entity))
                self._maybe_autocomplete_job(job_id)
        else:
            # No retry budget configured (max_task_retries=0): the
            # legacy fail-fast contract — terminal "failed", exit -4.
            try:
                self._merge_task(job_id, task_id, {
                    "state": "failed", "exit_code": -4,
                    "error": reason,
                    "completed_at": util.datetime_utcnow_iso()},
                    if_match=entity["_etag"])
            except (EtagMismatchError, NotFoundError):
                self.store.delete_message(msg)
                return
            self._clear_gang_history(job_id, task_id,
                                     self._gang_attempt(entity))
            self._maybe_autocomplete_job(job_id)
        self.store.delete_message(msg)

    def _run_gang_instance(self, slot: int, job_id: str, task_id: str,
                           entity: dict, instance: int, msg) -> None:
        spec = entity["spec"]
        # Elastic resize: the CURRENT attempt's effective size may be
        # below the spec's num_instances (gang_size stamped by
        # _recover_broken_gang when nodes were lost).
        num_instances = int(
            entity.get(names.TASK_COL_GANG_SIZE)
            or spec["multi_instance"]["num_instances"])
        if instance >= num_instances:
            # Stale message from a larger pre-resize attempt: this
            # instance index no longer exists at the current size —
            # joining would corrupt the smaller rendezvous.
            self.store.delete_message(msg)
            return
        gang_pk = self._gang_pk(job_id, task_id, entity)
        if not self._gang_claim(gang_pk, instance):
            # This node can't take this instance. Probe gang health at
            # most once per heartbeat interval per gang — the bounce
            # path spins during normal formation on large pools.
            probe_key = (job_id, task_id)
            now = time.monotonic()
            if now - self._gang_probe_at.get(probe_key, 0.0) > max(
                    1.0, self.heartbeat_interval):
                self._gang_probe_at[probe_key] = now
                members = self._gang_members(gang_pk)
                if (len(members) >= num_instances and all(
                        m.get("state") == "done" for m in members)):
                    # Whole gang finished but the last member crashed
                    # between marking done and finalizing: finish the
                    # aggregation on its behalf.
                    self._gang_finalize(job_id, task_id, gang_pk,
                                        num_instances)
                    self.store.delete_message(msg)
                    self._maybe_autocomplete_job(job_id)
                    return
                stale = self._stale_gang_members(members)
                if stale:
                    self._recover_broken_gang(
                        job_id, task_id, gang_pk, stale, msg,
                        attempt=self._gang_attempt(entity))
                    return
            # Otherwise make the message promptly available for other
            # nodes.
            self.store.update_message(msg, visibility_timeout=0.0)
            time.sleep(self.poll_interval)
            return
        try:
            self._run_gang_claimed(slot, job_id, task_id, entity,
                                   instance, msg, gang_pk,
                                   num_instances, spec)
        finally:
            # Release the slot-local claim registration taken by
            # _gang_claim however we exit; a crash here leaves the
            # rows joined+ours, and the redelivered message resumes
            # them through _resume_own_gang_claim.
            with self._running_lock:
                self._active_gang_claims.discard((gang_pk, instance))

    def _run_gang_claimed(self, slot: int, job_id: str, task_id: str,
                          entity: dict, instance: int, msg,
                          gang_pk: str, num_instances: int,
                          spec: dict) -> None:
        """Post-claim gang path: rendezvous, run, aggregate. The
        caller holds this node's active-claim registration for
        (gang_pk, instance) and releases it when this returns."""
        self._goodput_work_started(slot, job_id, task_id, entity,
                                   emit_queued=(instance == 0))
        # Rendezvous: wait for all instances to join, watching for
        # members dying underneath us (preemption/crash).
        deadline = time.monotonic() + self.gang_timeout
        keepalive = time.monotonic()
        last_stale_check = 0.0
        rendezvous_started = time.time()
        while True:
            members = self._gang_members(gang_pk)
            if len(members) >= num_instances:
                break
            if time.monotonic() - last_stale_check > max(
                    1.0, self.heartbeat_interval):
                stale = self._stale_gang_members(members)
                if stale:
                    self._recover_broken_gang(
                        job_id, task_id, gang_pk, stale, msg,
                        attempt=self._gang_attempt(entity))
                    self._goodput_work_done(slot)
                    return
                last_stale_check = time.monotonic()
            if time.monotonic() > deadline:
                retries = int(entity.get("retries", 0))
                attempt = self._gang_attempt(entity)
                try:
                    fresh = self._task_entity(job_id, task_id)
                except NotFoundError:
                    fresh = None
                if (fresh is not None
                        and fresh.get("state")
                        not in names.TERMINAL_TASK_STATES
                        and self._gang_attempt(fresh) == attempt):
                    # Elastic gang stuck in FORMATION because the
                    # pool shrank below its size (members that never
                    # joined have no stale row to observe): re-form
                    # at what the pool can supply instead of failing
                    # — the resize analog of _recover_broken_gang.
                    eff_size, new_size = self._elastic_size(
                        spec, fresh)
                    if (new_size != eff_size
                            and self._retry_decision(
                                retries,
                                spec.get("max_task_retries", 0))
                            == "requeue"):
                        if self._requeue_with_backoff(
                                job_id, task_id, spec, retries + 1,
                                -1, "gang rendezvous timeout "
                                    "(resizing)",
                                instances=new_size,
                                if_match=fresh["_etag"],
                                extra={names.TASK_COL_GANG_SIZE:
                                       new_size
                                       if new_size != spec[
                                           "multi_instance"][
                                           "num_instances"]
                                       else None}):
                            self._emit_gang_resize(
                                job_id, task_id, fresh, eff_size,
                                new_size, retries + 1)
                            self._clear_gang_rows(gang_pk)
                        self.store.delete_message(msg)
                        self._goodput_work_done(slot)
                        return
                    try:
                        self._merge_task(job_id, task_id, {
                            "state": "failed", "exit_code": -1,
                            "error": "gang rendezvous timeout",
                            "completed_at":
                                util.datetime_utcnow_iso()},
                            if_match=fresh["_etag"])
                    except (EtagMismatchError, NotFoundError):
                        # A peer recovered/terminated the task
                        # concurrently — its transition wins.
                        self.store.delete_message(msg)
                        self._goodput_work_done(slot)
                        return
                    # Terminal: retire the rendezvous rows now, not
                    # at the janitor's next leader pass.
                    self._clear_gang_history(job_id, task_id, attempt)
                self.store.delete_message(msg)
                self._goodput_work_done(slot)
                return
            if self.stop_event.is_set():
                self._goodput_work_done(slot)
                return
            # Renew the claim on the same cadence as
            # _message_keepalive: the visibility window is
            # configurable (drills shrink it below the old hardcoded
            # 30s renew), and a lapsed window mid-rendezvous means
            # duplicate redeliveries churning the bounce path.
            if time.monotonic() - keepalive > max(
                    0.5, self.claim_visibility_seconds / 3.0):
                self.store.update_message(
                    msg,
                    visibility_timeout=self.claim_visibility_seconds)
                keepalive = time.monotonic()
            time.sleep(self.poll_interval)
        # Full formation: the rendezvous span is per INSTANCE (each
        # member's own wait — the straggler analysis the gang
        # scheduler needs is exactly the spread of these).
        trace_spans.emit(
            self.store, self.identity.pool_id,
            trace_spans.SPAN_RENDEZVOUS,
            trace_context.TraceContext.from_entity(entity),
            job_id=job_id, task_id=task_id,
            node_id=self.identity.node_id,
            start=rendezvous_started, end=time.time(),
            attrs={"instance": instance,
                   "gang_size": num_instances,
                   "attempt": self._gang_attempt(entity)})
        if instance == 0:
            try:
                self._merge_task(job_id, task_id, {
                    "state": "running",
                    "started_at": util.datetime_utcnow_iso(),
                    # Recovery intervals closed by this attempt (the
                    # gang analog of _claim_regular's clear).
                    names.TASK_COL_PREEMPTED_AT: None,
                    names.TASK_COL_EVICTED_AT: None})
            except NotFoundError:
                pass
        gang_members = [
            launcher.GangMember(
                instance=int(m["_rk"][1:]), node_id=m["node_id"],
                hostname=m["hostname"], internal_ip=m["internal_ip"],
                slice_index=m.get("slice_index", 0),
                worker_index=m.get("worker_index", 0))
            for m in sorted(self._gang_members(gang_pk),
                            key=lambda e: int(e["_rk"][1:]))]
        me = next(m for m in gang_members if m.instance == instance)
        mi = _mi_settings_from_spec(spec["multi_instance"],
                                    num_instances=num_instances)
        gang_env = launcher.synthesize_gang_env(
            gang_members, me, mi, self.pool)
        with self._message_keepalive(msg):
            jp_ok = self._ensure_job_prep(job_id, spec)
            try:
                self._ensure_images_timed(job_id, task_id, spec,
                                          entity=entity)
                execution = self._build_execution(
                    slot, job_id, task_id, spec, instance=instance,
                    instances=num_instances,
                    host_list=tuple(m.internal_ip
                                    for m in gang_members),
                    extra_env=gang_env, entity=entity)
            except TaskEnvError as exc:
                # Record the instance failure through the normal gang
                # aggregation (a raise here would bounce the message
                # forever — the same hazard as the scratch-mount
                # failure above), and surface the REASON on the task
                # entity so the user doesn't have to grep node logs.
                logger.error("gang %s/%s i%d: %s", job_id, task_id,
                             instance, exc)
                try:
                    self._merge_task(job_id, task_id,
                                     {"error": str(exc)})
                except NotFoundError:
                    pass
                jp_ok = False
                execution = self._build_execution(
                    slot, job_id, task_id,
                    {**spec, "environment_variables": {},
                     "environment_variables_secret_id": None},
                    instance=instance, instances=num_instances,
                    host_list=tuple(m.internal_ip
                                    for m in gang_members),
                    extra_env=gang_env, entity=entity)
            try:
                self._stage_inputs(spec, execution)
            except Exception as exc:
                logger.exception("gang input staging failed for %s/%s",
                                 job_id, task_id)
                jp_ok = False
            with self._running_lock:
                self._running_tasks += 1
            try:
                if not jp_ok:
                    result = task_runner.TaskResult(
                        exit_code=-2, stdout_path="", stderr_path="",
                        started_at=util.datetime_utcnow_iso(),
                        completed_at=util.datetime_utcnow_iso(),
                        wall_seconds=0.0)
                else:
                    if spec["multi_instance"].get("coordination_command"):
                        coordination = dataclasses.replace(
                            execution,
                            command=spec["multi_instance"][
                                "coordination_command"],
                            task_dir=os.path.join(
                                execution.task_dir, "coord"))
                        task_runner.run_task(coordination)
                    # Register the live proc like the regular path:
                    # term_task control verbs and chaos task_kill/
                    # task_wedge injections target gang instances too.
                    # The slot ledger is armed as a GANG record: a
                    # restarted agent cannot re-join the in-memory
                    # rendezvous this launch belonged to, but it must
                    # learn a member process may still be alive and
                    # fence it before the gang's requeue re-runs it.
                    result = self._run_task_registered(
                        (job_id, task_id), execution,
                        ledger_slot=slot, ledger_gang=True)
            finally:
                with self._running_lock:
                    self._running_tasks -= 1
        if self._abandoned:
            # Simulated agent-process death mid-gang-run (chaos
            # agent_restart): a dead process writes nothing — the
            # gang's recovery paths own the task from here.
            return
        # The member process exited and we're alive to record it: the
        # gang ledger's only job (fencing a leftover live process on
        # restart) is done.
        self._clear_slot_ledger(slot, (job_id, task_id))
        gang_evicted = (job_id, task_id) in self._evicted_locally
        self._evicted_locally.discard((job_id, task_id))
        self._note_task_outcome(
            result.exit_code == 0, wedged=result.wedged,
            neutral=(result.exit_code == preempt_mod.EXIT_PREEMPTED
                     or gang_evicted))
        try:
            self.store.merge_entity(
                names.TABLE_GANGS, gang_pk, f"i{instance}",
                {"state": "done", "exit_code": result.exit_code})
        except NotFoundError:
            # The gang was recovered (requeued under a new attempt
            # partition) while this instance was running: its result
            # belongs to a superseded attempt. Clean up and bow out —
            # the rerun owns the task entity now.
            logger.warning(
                "gang %s/%s i%d finished after the gang was "
                "recovered; discarding superseded result",
                job_id, task_id, instance)
            self._goodput_task_finished(slot, job_id, task_id, result,
                                        entity=entity,
                                        instance=instance)
            self.store.delete_message(msg)
            return
        try:
            self._upload_outputs(job_id, task_id, execution,
                                 suffix=f"i{instance}")
        except Exception as exc:  # noqa: BLE001 - classify anyway
            # Same rule as _finish_regular_result: the gang finalize
            # below must run even when the blob upload fails.
            logger.exception("gang output upload failed for %s/%s",
                             job_id, task_id)
            try:
                self._merge_task(job_id, task_id,
                                 {"output_error": str(exc)})
            except Exception:  # noqa: BLE001 - best effort
                pass
        self._ingest_goodput(job_id, task_id, execution)
        self._upload_profile_artifacts(job_id, task_id, execution,
                                       suffix=f"i{instance}")
        self._export_compile_cache()
        self._goodput_task_finished(slot, job_id, task_id, result,
                                    entity=entity, instance=instance)
        try:
            self._collect_outputs(spec, execution, job_id, task_id)
        except Exception as exc:
            logger.exception("gang output collection failed for %s/%s",
                             job_id, task_id)
            self._merge_task(job_id, task_id,
                             {"output_error": str(exc)})
        self._schedule_retention(spec, job_id, task_id)
        self.store.delete_message(msg)
        self._gang_finalize(job_id, task_id, gang_pk, num_instances)
        self._maybe_autocomplete_job(job_id)

    def _gang_finalize(self, job_id: str, task_id: str, gang_pk: str,
                       num_instances: int) -> None:
        """Last instance to finish aggregates the gang exit code. A
        failing gang (any nonzero member) retries WHOLE — same
        supervisor as regular tasks: backoff requeue within the
        budget (the rerun restores from the committed checkpoint),
        quarantine past it."""
        members = self._gang_members(gang_pk)
        done = [m for m in members if m.get("state") == "done"]
        if len(done) < num_instances:
            return
        # First nonzero wins (max() would mask negative signal-kill
        # codes behind a zero).
        nonzero = [m.get("exit_code", 0) for m in done
                   if m.get("exit_code", 0) != 0]
        exit_code = nonzero[0] if nonzero else 0
        # A gang is preempted only when EVERY nonzero member drained
        # cooperatively: one real failure among the 75s is a failure
        # (the retry supervisor's budget applies), not a preemption.
        if nonzero and all(c == preempt_mod.EXIT_PREEMPTED
                           for c in nonzero):
            exit_code = preempt_mod.EXIT_PREEMPTED
        elif exit_code == preempt_mod.EXIT_PREEMPTED:
            exit_code = next(c for c in nonzero
                             if c != preempt_mod.EXIT_PREEMPTED)
        try:
            entity = self._task_entity(job_id, task_id)
        except NotFoundError:
            return
        if entity.get("state") in names.TERMINAL_TASK_STATES or \
                entity.get("state") in names.CLAIMABLE_TASK_STATES:
            # Terminal, or already requeued (pending/preempted) by a
            # concurrent recoverer — nothing left to aggregate.
            return
        spec = entity["spec"]
        retries = int(entity.get("retries", 0))
        max_retries = spec.get("max_task_retries", 0)
        # An escalated request on the entity classifies a nonzero
        # gang exit as evicted: every member was hard-killed (or died
        # with the kill racing its own exit) — one externally-caused
        # transition for the whole gang, never a budgeted failure.
        request = entity.get(names.TASK_COL_PREEMPT_REQUEST)
        evicted = (exit_code not in (0, preempt_mod.EXIT_PREEMPTED)
                   and isinstance(request, dict)
                   and bool(request.get("escalated_at")))
        decision = ("complete" if exit_code == 0
                    else "preempted"
                    if exit_code == preempt_mod.EXIT_PREEMPTED
                    else "evicted" if evicted
                    else self._retry_decision(retries, max_retries))
        if decision == "evicted":
            if self._requeue_evicted(job_id, task_id, spec,
                                     instances=num_instances):
                self._clear_gang_rows(gang_pk)
                return
            decision = self._retry_decision(retries, max_retries)
        if decision == "preempted":
            # The whole gang drained cooperatively (every member ran
            # the same preempt-aware program): requeue all instances
            # at full budget. The effective size is preserved — a
            # resized gang stays at its size until a recovery path
            # recomputes it from live capacity.
            if self._requeue_preempted(job_id, task_id, spec,
                                       instances=num_instances):
                self._clear_gang_rows(gang_pk)
                return
            # No pending request (spurious 75) or a lost merge: the
            # retry supervisor prices it like any failure.
            decision = self._retry_decision(retries, max_retries)
        if decision == "requeue":
            # The rerun's size follows live capacity too: a gang
            # whose members were killed by dying nodes finalizes with
            # their exit codes (the nodes' threads flushed them
            # before dying), and requeuing at the spec size onto a
            # shrunken pool would wedge the rendezvous.
            eff_size, new_size = self._elastic_size(spec, entity)
            if self._requeue_with_backoff(
                    job_id, task_id, spec, retries + 1, exit_code,
                    f"gang exit code {exit_code}",
                    instances=new_size,
                    if_match=entity["_etag"],
                    extra={names.TASK_COL_GANG_SIZE:
                           new_size
                           if new_size != spec["multi_instance"][
                               "num_instances"]
                           else None}):
                if new_size != eff_size:
                    self._emit_gang_resize(job_id, task_id, entity,
                                           eff_size, new_size,
                                           retries + 1)
                self._clear_gang_rows(gang_pk)
            return
        if decision == "quarantine":
            if self._quarantine_task(
                    job_id, task_id, exit_code,
                    f"gang exit code {exit_code}",
                    if_match=entity["_etag"]):
                self._clear_gang_history(job_id, task_id,
                                         self._gang_attempt(entity))
            return
        try:
            self._merge_task(job_id, task_id, {
                "state": "completed" if exit_code == 0 else "failed",
                "exit_code": exit_code,
                "completed_at": util.datetime_utcnow_iso(),
            }, if_match=entity["_etag"])
        except (EtagMismatchError, NotFoundError):
            return
        # Terminal: retire the rendezvous partitions (every attempt)
        # so no gang rows outlive their task (the drill's
        # no-orphaned-state invariant). Late zombie members of this
        # attempt get NotFoundError on their done-merge and bow out.
        self._clear_gang_history(job_id, task_id,
                                 self._gang_attempt(entity))

    # --------------------------- helpers -------------------------------

    def _resolve_env_secrets(self, env: dict) -> dict:
        """Resolve secret:// values in task/job environment_variables
        ON NODE at launch time (reference analog: convoy/batch.py
        :4556-4577 merges keyvault secret ids into per-task env, with
        on-node decrypt via nodeprep :1281). The state store only ever
        holds the refs; the plaintext exists in the task process env
        and nowhere else. SHIPYARD_SECRETS_FILE points the agent at a
        file-provider secrets YAML when one is used."""
        from batch_shipyard_tpu.utils import secrets as secrets_mod
        resolved = {}
        secrets_file = os.environ.get("SHIPYARD_SECRETS_FILE")
        for key, value in env.items():
            if secrets_mod.is_secret_id(value):
                value = secrets_mod.resolve_secret(
                    value, secrets_file=secrets_file)
            resolved[key] = value
        return resolved

    def _resolve_env_block(self, job_id: str,
                           secret_id: str) -> dict:
        """Resolve a secret holding a WHOLE env-var map (YAML/JSON
        mapping, or KEY=VALUE lines) — the reference's
        environment_variables_keyvault_secret_id (keyvault.py:176).
        Explicit per-key env always wins over the block. Cached per
        (job, secret) so a 1000-task job costs one provider round
        trip per node. Raises ValueError on an unparseable/empty
        block — running a task silently missing its env vars is
        worse than failing it."""
        cache_key = (job_id, secret_id)
        cached = self._env_block_cache.get(cache_key)
        if cached is not None:
            return cached
        from batch_shipyard_tpu.utils import secrets as secrets_mod
        raw = secrets_mod.resolve_secret(
            secret_id,
            secrets_file=os.environ.get("SHIPYARD_SECRETS_FILE"))
        import yaml
        block = None
        try:
            # YAML is a JSON superset: one parse covers both
            # documented map formats. A dotenv line like
            # 'MSG=hello: world' also parses as a YAML mapping — but
            # with '=' inside the key, which no real env map has; in
            # that case fall through to the KEY=VALUE parser.
            parsed = yaml.safe_load(raw)
            if isinstance(parsed, dict) and not any(
                    "=" in str(k) for k in parsed):
                block = parsed
        except yaml.YAMLError:
            pass
        if block is None:
            block = {}
            for line in raw.splitlines():
                line = line.strip()
                if not line or line.startswith("#") or "=" not in line:
                    continue
                key, _, value = line.partition("=")
                block[key.strip()] = value.strip()
        if not block:
            raise ValueError(
                f"env-block secret {secret_id} resolved to no "
                f"variables (expect a YAML/JSON mapping or KEY=VALUE "
                f"lines)")
        resolved = {str(k): str(v) for k, v in block.items()}
        # Bounded: jobs that never trigger a release fan-out on this
        # node (no prep/inputs/scratch) must not pin secret material
        # in memory for the process lifetime.
        if len(self._env_block_cache) >= 32:
            self._env_block_cache.pop(
                next(iter(self._env_block_cache)))
        self._env_block_cache[cache_key] = resolved
        return resolved

    def _build_execution(self, slot: int, job_id: str, task_id: str,
                         spec: dict, instance: int = 0, instances: int = 1,
                         host_list: tuple[str, ...] = (),
                         extra_env: Optional[dict] = None,
                         entity: Optional[dict] = None,
                         ) -> task_runner.TaskExecution:
        from batch_shipyard_tpu.utils import secrets as secrets_mod
        try:
            env = self._resolve_env_secrets(
                dict(spec.get("environment_variables", {})))
            env_secret = spec.get("environment_variables_secret_id")
            if env_secret:
                for key, value in self._resolve_env_block(
                        job_id, env_secret).items():
                    env.setdefault(key, value)
        except (secrets_mod.SecretResolutionError, ValueError) as exc:
            raise TaskEnvError(
                f"environment synthesis failed: {exc}") from exc
        env["SHIPYARD_JOB_SHARED_DIR"] = self._job_shared_dir(job_id)
        if spec.get("auto_scratch"):
            try:
                env["SHIPYARD_JOB_SCRATCH"] = self._resolve_scratch(
                    job_id, spec)
            except RuntimeError:
                # Shared-scratch resolution can only fail here when
                # job prep already failed on this node (success caches
                # the path) — the task will not run, but the gang path
                # still needs a constructible execution to record the
                # instance's failure instead of bouncing the message
                # forever.
                env["SHIPYARD_JOB_SCRATCH"] = \
                    self._job_scratch_dir(job_id)
        if extra_env:
            env.update(extra_env)
        task_dir = os.path.join(
            self.work_dir, "tasks", job_id, task_id,
            f"i{instance}" if instances > 1 else "")
        # Program-phase goodput sink: workloads record compile / step
        # windows / checkpoint spans here; the agent ingests the file
        # into TABLE_GOODPUT after the task exits.
        env.setdefault(
            goodput_events.GOODPUT_FILE_ENV,
            os.path.join(task_dir.rstrip("/"),
                         "goodput_events.jsonl"))
        # Wedge-watchdog liveness file: instrumented workloads beat it
        # every step (agent/progress.py); the task runner kills tasks
        # whose spec declares progress_deadline_seconds when it goes
        # stale.
        env.setdefault(
            progress_mod.PROGRESS_FILE_ENV,
            os.path.join(task_dir.rstrip("/"), "progress_beat"))
        if spec.get("progress_deadline_seconds") is not None:
            # Export the deadline too: beat() scales its write
            # throttle to it, so a tight deadline can't be starved by
            # the throttle itself.
            env.setdefault(
                progress_mod.PROGRESS_DEADLINE_ENV,
                str(spec["progress_deadline_seconds"]))
        # Scheduling-hints contract: instrumented workloads publish
        # {step, ckpt_step, step_seconds, cache_identity} here
        # (agent/progress.py record_sched_hints); the heartbeat loop
        # mirrors the file into the task row's sched_hints column for
        # the preemption sweep's victim-cost policy.
        env.setdefault(
            progress_mod.SCHED_HINTS_FILE_ENV,
            os.path.join(task_dir.rstrip("/"), "sched_hints.json"))
        # Declared compile-cache identity (claim affinity's key),
        # exported so the workload enables the persistent cache under
        # the same identity the scheduler placed it by.
        if spec.get("compile_cache_identity"):
            env.setdefault("SHIPYARD_COMPILE_CACHE_IDENTITY",
                           str(spec["compile_cache_identity"]))
        # Cooperative-preemption contract: the heartbeat loop drops a
        # preempt request here; instrumented workloads poll it each
        # step (PreemptWatcher), drain, force-commit, and exit
        # EXIT_PREEMPTED.
        env.setdefault(
            preempt_mod.PREEMPT_REQUEST_FILE_ENV,
            os.path.join(task_dir.rstrip("/"),
                         "preempt_request.json"))
        # A request file left by a PREVIOUS attempt must not drain
        # the new one on its first step: the request was consumed by
        # the attempt it preempted (the .delivered marker keeps the
        # heartbeat loop from re-dropping that requested_at), so the
        # rerun starts clean.
        try:
            os.remove(env[preempt_mod.PREEMPT_REQUEST_FILE_ENV])
        except OSError:
            pass
        # Distributed-trace contract: the task row's context is
        # exported so every program span/goodput event the process
        # records parents under the task's run span; the JSONL span
        # sink is ingested post-task like the goodput file.
        ctx = trace_context.TraceContext.from_entity(entity or {})
        if ctx is not None:
            for key, value in ctx.env().items():
                env.setdefault(key, value)
            env.setdefault(
                trace_context.TRACE_FILE_ENV,
                os.path.join(task_dir.rstrip("/"),
                             "trace_spans.jsonl"))
        # On-demand profiling contract: the harness watches the
        # request file (trace/profiling.StepProfiler) and writes the
        # jax.profiler artifact into the profile dir, which the agent
        # uploads post-task. A request already pending at launch is
        # delivered right here; requests arriving mid-run are
        # delivered by the heartbeat loop.
        env.setdefault(
            trace_profiling.PROFILE_REQUEST_FILE_ENV,
            os.path.join(task_dir.rstrip("/"),
                         "profile_request.json"))
        env.setdefault(
            trace_profiling.PROFILE_DIR_ENV,
            os.path.join(task_dir.rstrip("/"), "profile"))
        request = self._cached_job_profile_request(job_id)
        if request is not None:
            # Launch-time delivery goes straight to this instance's
            # env path (the task dir may not exist yet —
            # write_request creates it); the per-path dedup keeps the
            # heartbeat loop from re-dropping the same request after
            # the harness consumed it, without starving sibling gang
            # instances of their own copies.
            self._deliver_profile_file(
                env[trace_profiling.PROFILE_REQUEST_FILE_ENV],
                request)
        # Warm-start compilation: every task sees the node's
        # persistent compile cache dir, seeded from the pool artifact
        # just before launch so restarts and late pool joiners
        # deserialize instead of compiling.
        env.setdefault(cc_manager.CACHE_DIR_ENV,
                       self._compile_cache_dir())
        with trace_spans.span(
                self.store, self.identity.pool_id,
                trace_spans.SPAN_CACHE_SEED, ctx, job_id=job_id,
                task_id=task_id, node_id=self.identity.node_id):
            self._seed_compile_cache()
        return task_runner.TaskExecution(
            pool_id=self.identity.pool_id, job_id=job_id, task_id=task_id,
            node_id=self.identity.node_id,
            node_index=self.identity.node_index,
            command=spec.get("command", ""),
            runtime=spec.get("runtime", "none"),
            container_runtime=spec.get("container_runtime", "runc"),
            image=spec.get("image"),
            env=env, task_dir=task_dir.rstrip("/"), slot=slot,
            instances=instances, instance=instance, host_list=host_list,
            max_wall_time_seconds=spec.get("max_wall_time_seconds"),
            progress_deadline_seconds=spec.get(
                "progress_deadline_seconds"),
            remove_container_after_exit=spec.get(
                "remove_container_after_exit", True),
            shm_size=spec.get("shm_size"),
            additional_docker_run_options=tuple(
                spec.get("additional_docker_run_options", [])),
            additional_singularity_options=tuple(
                spec.get("additional_singularity_options", [])),
            # Crash-restart adoption contract: the task's exit code
            # is persisted in its task_dir so a restarted agent can
            # classify an exit it never wait()ed on.
            record_exit_code=True,
        )

    def _ensure_job_prep(self, job_id: str, spec: dict,
                         wait_timeout: float = 600.0) -> bool:
        """Run job preparation exactly once per (job, node); other slots
        wait for it. Returns False if prep failed — the caller must not
        run the task on this node (Azure Batch jobPreparationTask
        semantics)."""
        jp_command = spec.get("job_preparation_command")
        job_inputs = spec.get("job_input_data") or []
        auto_scratch = spec.get("auto_scratch")
        if not jp_command and not job_inputs and not auto_scratch:
            return True
        pk = names.task_pk(self.identity.pool_id, job_id)
        try:
            self.store.insert_entity(
                names.TABLE_JOBPREP, pk, self.identity.node_id,
                {"state": "running", "at": util.datetime_utcnow_iso()})
        except EntityExistsError:
            # Another slot owns prep: wait for completion.
            deadline = time.monotonic() + wait_timeout
            while time.monotonic() < deadline:
                row = self.store.get_entity(
                    names.TABLE_JOBPREP, pk, self.identity.node_id)
                if row.get("state") == "done":
                    return row.get("exit_code", 0) == 0
                if self.stop_event.is_set():
                    return False
                time.sleep(self.poll_interval)
            return False
        exit_code = 0
        try:
            if auto_scratch:
                # Per-job scratch with job lifetime (BeeOND analog):
                # created here, removed by job release.
                os.makedirs(self._resolve_scratch(job_id, spec),
                            exist_ok=True)
            # Job-level input_data lands in the job's shared dir
            # (exposed to tasks as SHIPYARD_JOB_SHARED_DIR; the
            # $AZ_BATCH_NODE_SHARED_DIR analog).
            if job_inputs:
                from batch_shipyard_tpu.data import movement
                shared = self._job_shared_dir(job_id)
                os.makedirs(shared, exist_ok=True)
                movement.stage_task_inputs(
                    self.store,
                    self._resolved_inputs(
                        {"input_data": job_inputs}, job_id),
                    shared)
            if jp_command:
                jp_env = {
                    **self._resolve_env_secrets(
                        dict(spec.get("environment_variables", {}))),
                    "SHIPYARD_JOB_SHARED_DIR":
                        self._job_shared_dir(job_id),
                }
                if auto_scratch:
                    # Prep commands pre-populate scratch (the
                    # canonical BeeOND prep pattern).
                    jp_env["SHIPYARD_JOB_SCRATCH"] = (
                        self._resolve_scratch(job_id, spec))
                execution = task_runner.TaskExecution(
                    pool_id=self.identity.pool_id, job_id=job_id,
                    task_id="jobprep",
                    node_id=self.identity.node_id,
                    node_index=self.identity.node_index,
                    command=jp_command, runtime="none",
                    env=jp_env,
                    task_dir=os.path.join(self.work_dir, "jobprep",
                                          job_id))
                exit_code = task_runner.run_task(execution).exit_code
        except Exception as exc:
            logger.exception("job prep failed for %s", job_id)
            exit_code = -3
        self.store.merge_entity(
            names.TABLE_JOBPREP, pk, self.identity.node_id,
            {"state": "done", "exit_code": exit_code})
        return exit_code == 0

    def _job_shared_dir(self, job_id: str) -> str:
        return os.path.join(self.work_dir, "shared", job_id)

    def _job_scratch_dir(self, job_id: str) -> str:
        return os.path.join(self.work_dir, "scratch", job_id)

    def _resolve_scratch(self, job_id: str, spec: dict) -> str:
        """The job's scratch path on THIS node.

        auto_scratch: true   -> node-local dir (BeeOND-lite).
        auto_scratch: shared -> ONE POSIX namespace across the gang
        (the reference's BeeOND shared parallel fs,
        shipyard_auto_scratch.sh:1-82): worker 0 hosts the directory,
        exports it over NFS, and publishes {path, host_ip} in the
        jobprep table; other workers reuse the path directly when it
        is visible on their filesystem (fake/localhost substrates) or
        NFS-mount it (real multi-VM pools)."""
        if spec.get("auto_scratch") != "shared":
            return self._job_scratch_dir(job_id)
        cached = self._shared_scratch.get(job_id)
        if cached is not None:
            return cached
        pk = names.task_pk(self.identity.pool_id, job_id)
        if self.identity.node_index == 0:
            path = self._job_scratch_dir(job_id)
            os.makedirs(path, exist_ok=True)
            # Nonce: lets non-host workers decide "same filesystem"
            # by reading it THROUGH the published path rather than by
            # bare directory existence (a stale preserved scratch at
            # the identical layout path would otherwise silently
            # become a private local dir).
            nonce = uuid.uuid4().hex
            with open(os.path.join(path, _SCRATCH_NONCE), "w",
                      encoding="utf-8") as fh:
                fh.write(nonce)
            rc = self._scratch_export(path)
            if rc != 0:
                raise RuntimeError(
                    f"job {job_id}: NFS export of shared scratch "
                    f"{path} failed rc={rc}")
            self.store.upsert_entity(
                names.TABLE_JOBPREP, pk, "#scratchhost", {
                    "path": path,
                    "host_ip": self.identity.internal_ip,
                    "node_id": self.identity.node_id,
                    "nonce": nonce})
            self._shared_scratch[job_id] = path
            return path
        deadline = time.monotonic() + 60.0
        while True:
            try:
                row = self.store.get_entity(
                    names.TABLE_JOBPREP, pk, "#scratchhost")
                break
            except NotFoundError:
                if time.monotonic() > deadline:
                    raise RuntimeError(
                        f"job {job_id}: shared scratch host never "
                        f"published (is worker 0 alive?)")
                time.sleep(self.poll_interval)
        host_path = row["path"]
        if not self._force_remote_scratch and \
                self._nonce_matches(host_path, row.get("nonce")):
            # Same filesystem (fake/localhost substrates): the host
            # path IS the shared namespace.
            self._shared_scratch[job_id] = host_path
            return host_path
        mount_point = os.path.join(self.work_dir, "scratch-nfs",
                                   job_id)
        os.makedirs(mount_point, exist_ok=True)
        rc = self._scratch_mount(
            f"{row['host_ip']}:{host_path}", mount_point)
        if rc != 0:
            raise RuntimeError(
                f"job {job_id}: NFS mount of shared scratch "
                f"{row['host_ip']}:{host_path} failed rc={rc}")
        self._shared_scratch[job_id] = mount_point
        return mount_point

    @staticmethod
    def _nonce_matches(host_path: str, nonce: Optional[str]) -> bool:
        if not nonce:
            return False
        try:
            with open(os.path.join(host_path, _SCRATCH_NONCE),
                      encoding="utf-8") as fh:
                return fh.read().strip() == nonce
        except OSError:
            return False

    # Default NFS plumbing (used when no runner is injected). Export
    # and unexport are no-ops without exportfs/root — the
    # same-filesystem substrates don't need them.

    def _nfs_mount(self, remote: str, mount_point: str) -> int:
        return subprocess.call(["mount", "-t", "nfs", remote,
                                mount_point])

    def _nfs_umount(self, mount_point: str) -> int:
        return subprocess.call(["umount", mount_point])

    def _nfs_export(self, path: str) -> int:
        import shutil as shutil_mod
        if shutil_mod.which("exportfs") is None or os.geteuid() != 0:
            return 0
        line = f"{path} *(rw,sync,no_subtree_check,no_root_squash)"
        try:
            with open("/etc/exports", "r+", encoding="utf-8") as fh:
                if line not in fh.read():
                    fh.write(line + "\n")
            return subprocess.call(["exportfs", "-ra"])
        except OSError as exc:
            logger.warning("shared-scratch export failed: %s", exc)
            return 1

    def _nfs_unexport(self, path: str) -> int:
        """Remove the job's line from /etc/exports and re-sync —
        without this, root pools accumulate rw,no_root_squash exports
        of deleted paths across jobs."""
        import shutil as shutil_mod
        if shutil_mod.which("exportfs") is None or os.geteuid() != 0:
            return 0
        try:
            with open("/etc/exports", encoding="utf-8") as fh:
                lines = fh.readlines()
            keep = [ln for ln in lines
                    if not ln.startswith(path + " ")]
            if keep != lines:
                with open("/etc/exports", "w", encoding="utf-8") as fh:
                    fh.writelines(keep)
                return subprocess.call(["exportfs", "-ra"])
            return 0
        except OSError as exc:
            logger.warning("shared-scratch unexport failed: %s", exc)
            return 1

    def _release_shared_scratch(self, job_id: str) -> None:
        """End of a shared scratch's lifetime on this node. Mounters
        unmount and record completion; the host node records its own
        completion and DEFERS deletion to a finalize thread that
        waits for every jobprep-listed node to record release — a
        fan-out peer may still be harvesting through the mount, and
        an early rmtree would vanish data mid-copy."""
        path = self._shared_scratch.pop(job_id, None)
        pk = names.task_pk(self.identity.pool_id, job_id)
        if self.identity.node_index != 0:
            if path is not None and path.startswith(
                    os.path.join(self.work_dir, "scratch-nfs")):
                self._scratch_umount(path)
        try:
            self.store.merge_entity(names.TABLE_JOBPREP, pk,
                                    self.identity.node_id,
                                    {"released": True})
        except NotFoundError:
            pass
        if self.identity.node_index == 0:
            thread = threading.Thread(
                target=self._finalize_shared_scratch, args=(job_id,),
                name=f"scratch-fin-{job_id}", daemon=True)
            thread.start()
            self._threads.append(thread)

    def _finalize_shared_scratch(self, job_id: str) -> None:
        """Host-side deferred teardown: delete the exported tree only
        after the whole release fan-out has completed (or preserve it
        on timeout — a node that never finished harvesting must not
        lose its data)."""
        pk = names.task_pk(self.identity.pool_id, job_id)
        deadline = time.monotonic() + self._scratch_finalize_timeout
        while True:
            if self.stop_event.is_set():
                # Agent stopping mid-wait: a peer may still be
                # harvesting — preserve, exactly like the timeout path.
                logger.warning(
                    "job %s: agent stopping before release fan-out "
                    "completed; preserving shared scratch", job_id)
                return
            rows = [r for r in self.store.query_entities(
                        names.TABLE_JOBPREP, partition_key=pk)
                    if not r["_rk"].startswith("#")]
            if rows and all(r.get("released") for r in rows):
                break
            if time.monotonic() > deadline:
                # Preserve AND keep the export up: a merely-slow
                # peer may still be copying through its NFS mount;
                # revoking the export would kill its in-flight reads.
                logger.warning(
                    "job %s: release fan-out incomplete after %.0fs "
                    "(released: %s); preserving shared scratch (and "
                    "its export) for manual harvest", job_id,
                    self._scratch_finalize_timeout,
                    {r["_rk"]: bool(r.get("released")) for r in rows})
                return
            time.sleep(self.poll_interval)
        import shutil as shutil_mod
        self._scratch_unexport(self._job_scratch_dir(job_id))
        shutil_mod.rmtree(self._job_scratch_dir(job_id),
                          ignore_errors=True)
        try:
            self.store.delete_entity(names.TABLE_JOBPREP, pk,
                                     "#scratchhost")
        except NotFoundError:
            pass

    def _terminate_running_task(self, job_id: str,
                                task_id: str) -> None:
        """Kill a task's live process group (tasks term analog incl.
        the docker kill signal relay, batch.py:2630 — docker run
        processes are killed through their process group here)."""
        proc = self._live_procs.get((job_id, task_id))
        if proc is None:
            return
        import signal as signal_mod
        try:
            os.killpg(os.getpgid(proc.pid), signal_mod.SIGTERM)
        except (ProcessLookupError, PermissionError):
            pass

    def _upload_node_logs(self, max_bytes: int = 8 * 1024 * 1024
                          ) -> None:
        """Ship node-side logs to the object store (diag logs upload
        analog, batch.py:3151). Uploads the agent log (if present)
        and the nodeprep marker."""
        candidates = [
            os.path.join(self.work_dir, "agent.log"),
            os.path.join(os.path.dirname(self.work_dir), "agent.log"),
            os.path.join(self.work_dir, ".nodeprep_finished"),
        ]
        for path in candidates:
            if not os.path.exists(path):
                continue
            with open(path, "rb") as fh:
                data = fh.read(max_bytes)
            self.store.put_object(
                names.node_log_key(self.identity.pool_id,
                                   self.identity.node_id,
                                   os.path.basename(path)), data)

    def _install_ssh_key(self, username: str, public_key: str) -> None:
        """Append a public key to the agent user's authorized_keys
        (pool user add analog, batch.py:1045 add_ssh_user). On real
        nodes this manages ~username; under fake/localhost substrates
        it records into the work dir for inspection."""
        if not public_key:
            return
        ssh_dir = os.path.expanduser(f"~{username}/.ssh")
        if ssh_dir.startswith("~"):
            # User does not exist on this host (expanduser returned
            # the literal): record under the work dir instead.
            ssh_dir = os.path.join(self.work_dir, "ssh", username)
        try:
            os.makedirs(ssh_dir, mode=0o700, exist_ok=True)
        except (PermissionError, OSError):
            ssh_dir = os.path.join(self.work_dir, "ssh", username)
            os.makedirs(ssh_dir, exist_ok=True)
        auth = os.path.join(ssh_dir, "authorized_keys")
        existing = ""
        if os.path.exists(auth):
            with open(auth, "r", encoding="utf-8") as fh:
                existing = fh.read()
        if public_key.strip() not in existing:
            with open(auth, "a", encoding="utf-8") as fh:
                fh.write(public_key.strip() + "\n")
            os.chmod(auth, 0o600)

    def _remove_ssh_user(self, username: str) -> None:
        for base in (os.path.expanduser(f"~{username}/.ssh"),
                     os.path.join(self.work_dir, "ssh", username)):
            auth = os.path.join(base, "authorized_keys")
            if os.path.exists(auth):
                try:
                    os.remove(auth)
                except OSError:
                    pass

    def _cleanup_mi_containers(self) -> None:
        """Remove orphaned (exited/created, NOT running) shipyard-*
        containers (jobs cmi analog; reference reaps leftover MI
        coordination containers, batch.py:2322). Running task
        containers are never touched."""
        import shutil
        import subprocess
        if shutil.which("docker") is None:
            return
        names_seen: set[str] = set()
        for status in ("exited", "created", "dead"):
            rc, out, _err = util.subprocess_capture(
                ["docker", "ps", "-a", "--filter", "name=shipyard-",
                 "--filter", f"status={status}",
                 "--format", "{{.Names}}"])
            if rc == 0:
                names_seen.update(out.split())
        for name in names_seen:
            subprocess.call(["docker", "rm", "-f", name],
                            stdout=subprocess.DEVNULL,
                            stderr=subprocess.DEVNULL)

    def _run_job_release(self, job_id: str) -> None:
        for key in [k for k in self._env_block_cache
                    if k[0] == job_id]:
            self._env_block_cache.pop(key, None)
        try:
            job = self.store.get_entity(
                names.TABLE_JOBS, self.identity.pool_id, job_id)
        except NotFoundError:
            return
        spec = job.get("spec", {})
        jr_command = spec.get("job_release_command")
        if jr_command:
            # The shared dir may not exist yet (it is only created by
            # job-input staging) — release commands harvesting into it
            # must find it present.
            os.makedirs(self._job_shared_dir(job_id), exist_ok=True)
            jr_env = {"SHIPYARD_JOB_SHARED_DIR":
                      self._job_shared_dir(job_id)}
            if spec.get("auto_scratch"):
                # Release commands harvest scratch (archive/copy out)
                # BEFORE the rmtree below ends its lifetime.
                jr_env["SHIPYARD_JOB_SCRATCH"] = (
                    self._resolve_scratch(job_id, spec))
            execution = task_runner.TaskExecution(
                pool_id=self.identity.pool_id, job_id=job_id,
                task_id="jobrelease", node_id=self.identity.node_id,
                node_index=self.identity.node_index,
                command=jr_command, runtime="none", env=jr_env,
                task_dir=os.path.join(self.work_dir, "jobrelease",
                                      job_id))
            result = task_runner.run_task(execution)
            if result.exit_code != 0:
                logger.warning(
                    "job %s release command exited %d", job_id,
                    result.exit_code)
                if spec.get("auto_scratch"):
                    # The release command harvests scratch; if it
                    # failed, deleting scratch would irrecoverably
                    # destroy the un-harvested data. Leave it for the
                    # operator.
                    logger.warning(
                        "preserving job %s auto-scratch at %s for "
                        "manual harvest", job_id,
                        self._resolve_scratch(job_id, spec))
                    return
        if spec.get("auto_scratch") == "shared":
            self._release_shared_scratch(job_id)
        elif spec.get("auto_scratch"):
            # End of the scratch drive's lifetime (the release half of
            # the BeeOND analog).
            import shutil

            shutil.rmtree(self._job_scratch_dir(job_id),
                          ignore_errors=True)

    def _resolved_inputs(self, spec: dict, job_id: str) -> list[dict]:
        resolved = []
        for item in spec.get("input_data") or []:
            if item.get("kind") == "task_output":
                item = dict(item)
                item.setdefault("pool_id", self.identity.pool_id)
                item.setdefault("job_id", job_id)
            resolved.append(item)
        return resolved

    def _stage_inputs(self, spec: dict,
                      execution: task_runner.TaskExecution) -> None:
        input_data = self._resolved_inputs(spec, execution.job_id)
        if not input_data:
            return
        from batch_shipyard_tpu.data import movement
        os.makedirs(execution.task_dir, exist_ok=True)
        movement.stage_task_inputs(self.store, input_data,
                                   execution.task_dir)

    def _collect_outputs(self, spec: dict,
                         execution: task_runner.TaskExecution,
                         job_id: str, task_id: str) -> None:
        output_data = spec.get("output_data") or []
        if not output_data:
            return
        from batch_shipyard_tpu.data import movement
        exclude = movement.staged_input_rels(
            self.store, self._resolved_inputs(spec, job_id))
        movement.collect_task_outputs(
            self.store, output_data, execution.task_dir,
            self.identity.pool_id, job_id, task_id,
            exclude_rels=exclude)

    def _load_image_manifest(self, runtime: str) -> set:
        manifest = {
            row.get("image")
            for row in self.store.query_entities(
                names.TABLE_IMAGES,
                partition_key=self.identity.pool_id)
            if row.get("kind") == runtime}
        self._image_manifest_cache[runtime] = (
            time.monotonic() + 30.0, manifest)
        return manifest

    def _ensure_images(self, spec: dict) -> None:
        """Provision the task's image; with allow_run_on_missing_image
        false (the default), an image absent from the pool's
        replicated global resources FAILS the task instead of being
        pulled ad hoc (reference batch.py:4747 — missing images only
        run when the job opts in)."""
        image = spec.get("image")
        runtime = spec.get("runtime")
        if not image or runtime not in ("docker", "singularity"):
            return
        if not spec.get("allow_run_on_missing_image", False):
            cached = self._image_manifest_cache.get(runtime)
            if cached is not None and cached[0] > time.monotonic():
                manifest = cached[1]
            else:
                manifest = self._load_image_manifest(runtime)
            if image not in manifest:
                # The image may have been added moments ago (pool
                # images update racing the submit): refresh once
                # before declaring terminal failure.
                manifest = self._load_image_manifest(runtime)
            if image not in manifest:
                raise TaskEnvError(
                    f"image {image} is not in the pool's global "
                    f"resources and the job does not set "
                    f"allow_run_on_missing_image")
        if self._image_provisioner is not None:
            self._image_provisioner(self, [image], kind=runtime)

    def _upload_outputs(self, job_id: str, task_id: str,
                        execution: task_runner.TaskExecution,
                        suffix: str = "") -> None:
        for name in ("stdout.txt", "stderr.txt"):
            path = os.path.join(execution.task_dir, name)
            if not os.path.exists(path):
                continue
            key = names.task_output_key(
                self.identity.pool_id, job_id, task_id,
                f"{suffix}/{name}" if suffix else name)
            size = os.path.getsize(path)
            cap = self.output_upload_cap_bytes
            if cap is None or size <= cap:
                # Full upload, streamed — no whole-buffer read, no
                # silent 4MB truncation (round-1 weak #6).
                def chunks(p=path):
                    with open(p, "rb") as fh:
                        while True:
                            block = fh.read(_OUTPUT_STREAM_CHUNK)
                            if not block:
                                return
                            yield block
                self.store.put_object_stream(key, chunks())
            else:
                # Explicitly configured cap: keep head + tail around
                # an unmistakable marker instead of a silent cut.
                half = cap // 2
                with open(path, "rb") as fh:
                    head = fh.read(half)
                    fh.seek(max(size - half, half))
                    tail = fh.read()
                marker = (f"\n...[shipyard: output truncated, "
                          f"{size} bytes total, cap {cap}]...\n"
                          ).encode()
                self.store.put_object_stream(
                    key, iter([head, marker, tail]))

    def _maybe_autocomplete_job(self, job_id: str) -> None:
        """auto_complete: when every task of the job is terminal, mark
        the job completed and fan out job-release control messages
        (reference: on_all_tasks_complete / jobs term semantics)."""
        try:
            job = self.store.get_entity(
                names.TABLE_JOBS, self.identity.pool_id, job_id)
        except NotFoundError:
            return
        if not job.get("spec", {}).get("auto_complete"):
            return
        if job.get("state") != "active":
            return
        pk = names.task_pk(self.identity.pool_id, job_id)
        tasks = list(self.store.query_entities(
            names.TABLE_TASKS, partition_key=pk))
        if not tasks or any(
                t.get("state") not in names.TERMINAL_TASK_STATES
                for t in tasks):
            return
        try:
            self.store.merge_entity(
                names.TABLE_JOBS, self.identity.pool_id, job_id,
                {"state": "completed",
                 "completed_at": util.datetime_utcnow_iso()},
                if_match=job["_etag"])
        except (EtagMismatchError, NotFoundError):
            return
        # Fan out job release to nodes that ran job prep ("#"-prefixed
        # rows are metadata, e.g. the shared-scratch host record).
        for row in self.store.query_entities(
                names.TABLE_JOBPREP, partition_key=pk):
            if row["_rk"].startswith("#"):
                continue
            # Distinct per-node control queue each iteration — there
            # is nothing to batch.
            self.store.put_message(  # shipyard-lint: disable=store-write-in-loop
                names.control_queue(self.identity.pool_id, row["_rk"]),
                json.dumps({
                    "type": "job_release", "job_id": job_id}).encode())


def _mi_settings_from_spec(mi_spec: dict,
                           num_instances: Optional[int] = None
                           ) -> MultiInstanceSettings:
    """``num_instances`` overrides the spec's size — the elastic
    resize path runs the gang at the attempt's EFFECTIVE size, and
    the synthesized jax-distributed env must agree with the actual
    rendezvous width."""
    jd = mi_spec.get("jax_distributed", {})
    return MultiInstanceSettings(
        num_instances=(num_instances if num_instances is not None
                       else mi_spec["num_instances"]),
        min_instances=mi_spec.get("min_instances"),
        coordination_command=mi_spec.get("coordination_command"),
        resource_files=tuple(mi_spec.get("resource_files", [])),
        jax_distributed=JaxDistributedSettings(
            enabled=jd.get("enabled", True),
            coordinator_port=jd.get("coordinator_port", 8476),
            transport=jd.get("transport", "auto"),
            heartbeat_timeout_seconds=jd.get(
                "heartbeat_timeout_seconds", 100),
        ),
        pytorch_xla=mi_spec.get("pytorch_xla", {}).get("enabled", False),
    )

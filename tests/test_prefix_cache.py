"""Cross-request prefix/KV-cache reuse (models/serving.py): greedy
token-equivalence of shared-prefix decode vs the cold-prefill
baseline (dense reference, paged, speculative, int8 page scales) and
page-refcount invariants under admit/preempt/finish churn."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from batch_shipyard_tpu.models import inference as inf
from batch_shipyard_tpu.models import serving
from batch_shipyard_tpu.models import transformer as tfm

CFG = tfm.TransformerConfig(
    vocab_size=97, d_model=32, n_layers=2, n_heads=2, d_head=16,
    d_ff=64, max_seq_len=64, dtype=jnp.float32,
    param_dtype=jnp.float32)


@pytest.fixture(scope="module")
def params():
    model = tfm.TransformerLM(CFG)
    tokens = jnp.zeros((1, 8), jnp.int32)
    return model.init(jax.random.PRNGKey(7), tokens)["params"]


def reference_greedy(params, prompt, num_tokens):
    run, _model = inf.make_decoder(CFG, params, max_decode_len=64)
    tokens, _cache = run(jnp.asarray([prompt], jnp.int32), num_tokens,
                         jax.random.PRNGKey(0))
    return list(np.asarray(tokens[0, len(prompt):]))


def _drain(engine, steps=400):
    results = {}
    for _ in range(steps):
        for rid, toks in engine.step():
            results[rid] = toks
        if not engine.pending():
            break
    assert not engine.pending(), "engine failed to drain"
    return results


def _shared_prefix_requests(seed=0, base_pages=3, page=8, n=4):
    """One pilot request that publishes ``base_pages`` full pages,
    then n-1 followers sharing that prefix with distinct suffixes."""
    rng = np.random.RandomState(seed)
    base = list(rng.randint(0, 97, (base_pages * page,)))
    reqs = [serving.Request("pilot", base, max_new_tokens=5)]
    for i in range(n - 1):
        suffix = list(rng.randint(0, 97, (3 + 2 * i,)))
        reqs.append(serving.Request(f"fan{i}", base + suffix,
                                    max_new_tokens=4 + i))
    return reqs


def _check_invariants(engine):
    """The page lifecycle bookkeeping the prefix cache rests on:
    FREE / LRU / OWNED / PINNED partition the pool exactly, refcounts
    equal live slot references, and the availability counter matches
    total - pinned - reservations."""
    free = list(engine._free_pages)
    lru = list(engine._lru)
    owned = [p for pages in engine._slot_pages for p in pages]
    pinned = [pid for pid, ref in engine._page_ref.items() if ref > 0]
    assert set(lru) == {pid for pid, ref in engine._page_ref.items()
                        if ref == 0}
    everything = free + lru + owned + pinned
    assert len(everything) == len(set(everything)), \
        "a page appears in two lifecycle states at once"
    assert len(everything) == engine._total_pages, \
        "pages leaked or double-counted"
    live_refs: dict = {}
    for shared in engine._slot_shared:
        for pid in shared:
            live_refs[pid] = live_refs.get(pid, 0) + 1
    assert live_refs == {pid: ref
                         for pid, ref in engine._page_ref.items()
                         if ref > 0}, \
        "refcounts out of sync with slot references"
    assert engine._avail_pages == (
        engine._total_pages - len(pinned) -
        sum(engine._slot_reserved))


def test_shared_prefix_matches_cold_baseline(params):
    """Requests hitting a cached 3-page prefix produce EXACTLY the
    tokens cold batch-1 greedy decoding produces — and the shared
    prefill path demonstrably ran."""
    reqs = _shared_prefix_requests()
    engine = serving.ContinuousBatcher(
        CFG, params, num_slots=2, max_decode_len=64, kv_page_size=8)
    assert engine.prefix_cache
    for r in reqs:
        engine.submit(r)
    results = _drain(engine)
    assert engine.prefix_hit_pages >= 3 * (len(reqs) - 1), \
        "followers did not reuse the pilot's pages"
    stats = engine.prefix_stats()
    assert stats["hit_rate"] > 0.5
    assert stats["published_pages"] >= 3
    for r in reqs:
        want = reference_greedy(params, r.prompt, r.max_new_tokens)
        assert results[r.request_id] == want, r.request_id
    _check_invariants(engine)


def test_prefix_cache_off_is_cold_path(params):
    """prefix_cache=False never matches, never publishes, and still
    produces the reference outputs — the control arm of the bench."""
    reqs = _shared_prefix_requests()
    engine = serving.ContinuousBatcher(
        CFG, params, num_slots=2, max_decode_len=64, kv_page_size=8,
        prefix_cache=False)
    for r in reqs:
        engine.submit(r)
    results = _drain(engine)
    assert engine.prefix_hit_pages == 0
    assert engine.prefix_published == 0
    assert engine.prefix_stats() is None
    for r in reqs:
        assert results[r.request_id] == reference_greedy(
            params, r.prompt, r.max_new_tokens), r.request_id


def test_shared_prefix_speculative_exact(params):
    """Speculative decoding over shared prefixes stays greedy-exact:
    the draft prefills the full prompt (its dense-cache invariant),
    only the target skips the cached pages."""
    reqs = _shared_prefix_requests(seed=2)
    engine = serving.ContinuousBatcher(
        CFG, params, num_slots=2, max_decode_len=64, kv_page_size=8,
        speculative=serving.SpeculativeConfig(CFG, params, gamma=3))
    for r in reqs:
        engine.submit(r)
    results = _drain(engine)
    assert engine.prefix_hit_pages > 0
    for r in reqs:
        assert results[r.request_id] == reference_greedy(
            params, r.prompt, r.max_new_tokens), r.request_id
    _check_invariants(engine)


def test_shared_prefix_int8_pages_identical_to_cold(params):
    """int8 page pool: the gathered prefix rows carry their stored
    scales verbatim, so shared-prefix outputs are byte-identical to
    the prefix-cache-off int8 engine at the same requests."""
    cfg = dataclasses.replace(CFG, kv_cache_dtype="int8")
    outs = {}
    for on in (True, False):
        engine = serving.ContinuousBatcher(
            cfg, params, num_slots=2, max_decode_len=64,
            kv_page_size=8, prefix_cache=on)
        for r in _shared_prefix_requests(seed=3):
            engine.submit(r)
        outs[on] = _drain(engine)
        if on:
            assert engine.prefix_hit_pages > 0
    assert outs[True] == outs[False]


def test_refcount_invariants_under_churn(params):
    """Admit/preempt/finish churn on a deliberately tight overcommit
    pool with a shared prefix pinned across slots: after EVERY step,
    no page is freed while referenced, no page is double-owned, and
    the availability accounting balances; after drain, every page is
    reclaimable and no reference survives."""
    rng = np.random.RandomState(5)
    base = list(rng.randint(0, 97, (8,)))  # one shared page
    reqs = [serving.Request(
        f"c{i}", base + list(rng.randint(0, 97, (2 + i % 3,))),
        max_new_tokens=16) for i in range(6)]
    engine = serving.ContinuousBatcher(
        CFG, params, num_slots=2, max_decode_len=32, kv_page_size=8,
        kv_num_pages=5, overcommit=True)
    for r in reqs:
        engine.submit(r)
    results = {}
    for step in range(600):
        for rid, toks in engine.step():
            results[rid] = toks
        _check_invariants(engine)
        if step == 5:
            # Mid-flight cancel: an active slot's pages (shared AND
            # owned) must release cleanly.
            engine.cancel("c5")
        if not engine.pending():
            break
    assert engine.preemptions > 0, \
        "scenario failed to exercise preemption"
    done = {r.request_id for r in reqs} - {"c5"}
    assert done <= set(results)
    for rid in done:
        req = next(r for r in reqs if r.request_id == rid)
        assert results[rid] == reference_greedy(
            params, req.prompt, req.max_new_tokens), rid
    assert all(ref == 0 for ref in engine._page_ref.values())
    assert (len(engine._free_pages) + len(engine._lru)
            == engine._total_pages)


def test_lru_eviction_under_pool_pressure(params):
    """A full pool evicts unreferenced indexed pages (never pinned
    ones) to admit new work; the evicted prefix simply re-publishes
    on its next cold run."""
    rng = np.random.RandomState(6)
    engine = serving.ContinuousBatcher(
        CFG, params, num_slots=1, max_decode_len=32, kv_page_size=8,
        kv_num_pages=4)
    # Distinct 2-page prompts: each run parks 2 indexed pages; the
    # third admission must evict earlier LRU pages to reserve.
    for i in range(3):
        prompt = list(rng.randint(0, 97, (16,)))
        engine.submit(serving.Request(f"e{i}", prompt,
                                      max_new_tokens=4))
        results = _drain(engine)
        assert results[f"e{i}"] == reference_greedy(
            params, prompt, 4)
        _check_invariants(engine)
    assert engine.prefix_evictions > 0


def test_prefix_cache_clear_and_rewarm(params):
    """prefix_cache_clear reclaims every unreferenced indexed page;
    the same prompt afterwards misses, recomputes, republishes, and
    still matches the reference."""
    rng = np.random.RandomState(7)
    base = list(rng.randint(0, 97, (16,)))
    engine = serving.ContinuousBatcher(
        CFG, params, num_slots=1, max_decode_len=64, kv_page_size=8)
    engine.submit(serving.Request("a", base + [3], max_new_tokens=3))
    _drain(engine)
    published = engine.prefix_published
    assert published >= 2
    cleared = engine.prefix_cache_clear()
    assert cleared == len(engine._page_ref) == 0 or cleared >= 2
    assert len(engine._prefix_index) == 0
    hits_before = engine.prefix_hit_pages
    engine.submit(serving.Request("b", base + [9], max_new_tokens=3))
    results = _drain(engine)
    assert engine.prefix_hit_pages == hits_before  # cold again
    assert engine.prefix_published > published
    assert results["b"] == reference_greedy(params, base + [9], 3)
    _check_invariants(engine)


# ----------------------- bench phase (slow) ------------------------

@pytest.mark.slow
def test_bench_serving_slo_full_run():
    """The full serving_slo A/B phase (slow tier): regenerates the
    committed BENCH_serving_slo.json shape via exactly the call
    `python bench.py --workloads serving_slo` makes, and asserts the
    acceptance gates live — hit rate > 0.5, cache-on mean AND p99
    TTFT strictly below the cache-off control at the same seed, and
    byte-identical greedy outputs between the arms."""
    import pathlib
    import sys
    sys.path.insert(0, str(pathlib.Path(__file__).resolve().parents[1]))
    import bench
    result = bench.bench_serving_slo(artifact=False)
    assert result["cpu_marker"] is True
    assert result["prefix_hit_rate"] > 0.5
    assert result["outputs_identical"] is True
    on, off = result["prefix_cache_on"], result["prefix_cache_off"]
    assert on["completed"] == off["completed"] == \
        result["num_requests"]
    assert on["shed"] == off["shed"] == 0
    assert on["ttft_mean_ms"] < off["ttft_mean_ms"]
    assert on["ttft_exact_ms"]["p99"] < off["ttft_exact_ms"]["p99"]

"""Federation meta-scheduler tests: constraint filtering, greedy
best-fit, end-to-end scheduling onto fake pools, HA lock, zap."""

import json
import time

import pytest

from batch_shipyard_tpu.config import settings as settings_mod
from batch_shipyard_tpu.federation import federation as fed
from batch_shipyard_tpu.jobs import manager as jobs_mgr
from batch_shipyard_tpu.pool import manager as pool_mgr
from batch_shipyard_tpu.state.memory import MemoryStateStore
from batch_shipyard_tpu.substrate.fakepod import FakePodSubstrate

GLOBAL = settings_mod.global_settings({})


def make_pool(store, substrate, pool_id, accel="v5litepod-4"):
    conf = {"pool_specification": {
        "id": pool_id, "substrate": "fake",
        "tpu": {"accelerator_type": accel},
        "max_wait_time_seconds": 30}}
    pool = settings_mod.pool_settings(conf)
    pool_mgr.create_pool(store, substrate, pool, GLOBAL, conf)
    return pool


@pytest.fixture()
def env():
    store = MemoryStateStore()
    substrate = FakePodSubstrate(store)
    yield store, substrate
    substrate.stop_all()


def test_federation_crud(env):
    store, _ = env
    fed.create_federation(store, "f1")
    with pytest.raises(ValueError):
        fed.create_federation(store, "f1")
    fed.add_pool_to_federation(store, "f1", "pa")
    fed.add_pool_to_federation(store, "f1", "pb")
    assert fed.get_federation(store, "f1")["pools"] == ["pa", "pb"]
    fed.remove_pool_from_federation(store, "f1", "pa")
    assert fed.get_federation(store, "f1")["pools"] == ["pb"]
    fed.destroy_federation(store, "f1")
    with pytest.raises(ValueError):
        fed.get_federation(store, "f1")


def test_constraint_filter_and_best_fit(env):
    store, substrate = env
    make_pool(store, substrate, "small", "v5litepod-4")
    make_pool(store, substrate, "big", "v5litepod-16")
    facts = [f for f in (fed._pool_facts(store, p)
                         for p in ("small", "big")) if f]
    assert len(facts) == 2
    eligible = fed.filter_pools_hard_constraints(
        facts, {"min_chips": 8})
    assert [f["pool_id"] for f in eligible] == ["big"]
    # No constraints: best fit prefers most idle nodes (big pool).
    choice = fed.greedy_best_fit(
        fed.filter_pools_hard_constraints(facts, {}))
    assert choice["pool_id"] == "big"
    # Generation mismatch filters everything.
    assert fed.filter_pools_hard_constraints(
        facts, {"accelerator_generation": "v6e"}) == []


def test_end_to_end_federated_job(env):
    store, substrate = env
    make_pool(store, substrate, "cpuish", "v5litepod-4")
    make_pool(store, substrate, "podpool", "v5litepod-16")
    fed.create_federation(store, "fed1")
    fed.add_pool_to_federation(store, "fed1", "cpuish")
    fed.add_pool_to_federation(store, "fed1", "podpool")
    jobs_config = {"job_specifications": [{
        "id": "fj",
        "federation_constraints": {"min_chips": 16},
        "tasks": [{"command": "echo federated"}],
    }]}
    fed.submit_job_to_federation(store, "fed1", jobs_config)
    proc = fed.FederationProcessor(store)
    assert proc.process_once() == 1
    rows = fed.list_federation_jobs(store, "fed1")
    assert rows[0]["pool_id"] == "podpool"
    tasks = jobs_mgr.wait_for_tasks(store, "podpool", "fj", timeout=30)
    assert tasks[0]["state"] == "completed"


def test_unschedulable_job_requeues_then_schedules(env):
    store, substrate = env
    fed.create_federation(store, "fed2")
    jobs_config = {"job_specifications": [{
        "id": "fq", "tasks": [{"command": "echo late"}]}]}
    fed.submit_job_to_federation(store, "fed2", jobs_config)
    proc = fed.FederationProcessor(store, action_retry_delay=0.1)
    assert proc.process_once() == 0  # no pools yet -> backoff
    make_pool(store, substrate, "late-pool", "v5litepod-4")
    fed.add_pool_to_federation(store, "fed2", "late-pool")
    time.sleep(0.2)  # let the action become visible again
    assert proc.process_once() == 1
    jobs_mgr.wait_for_tasks(store, "late-pool", "fq", timeout=30)


def test_zap_drops_action(env):
    store, substrate = env
    fed.create_federation(store, "fed3")
    action_id = fed.submit_job_to_federation(
        store, "fed3", {"job_specifications": [{
            "id": "poison", "tasks": [{"command": "echo x"}]}]})
    fed.zap_action(store, "fed3", action_id)
    proc = fed.FederationProcessor(store)
    proc.process_once()
    from batch_shipyard_tpu.state import names
    assert store.queue_length(names.federation_queue("fed3")) == 0


def test_ha_single_scheduler(env):
    store, _ = env
    fed.create_federation(store, "fed4")
    proc_a = fed.FederationProcessor(store, owner="a")
    proc_b = fed.FederationProcessor(store, owner="b")
    assert proc_a._hold_global_lock()
    assert not proc_b._hold_global_lock()
    # a renews fine; b still locked out
    assert proc_a._hold_global_lock()
    assert not proc_b._hold_global_lock()


def test_federated_job_term_and_del_routing(env):
    store, substrate = env
    make_pool(store, substrate, "routed", "v5litepod-4")
    fed.create_federation(store, "fedr")
    fed.add_pool_to_federation(store, "fedr", "routed")
    fed.submit_job_to_federation(store, "fedr", {
        "job_specifications": [{
            "id": "rjob", "tasks": [{"command": "sleep 60"}]}]})
    fed.FederationProcessor(store).process_once()
    assert fed.locate_federation_job(store, "fedr",
                                     "rjob") == "routed"
    pool_id = fed.terminate_federation_job(store, "fedr", "rjob")
    assert pool_id == "routed"
    assert jobs_mgr.get_job(store, "routed", "rjob")[
        "state"] == "terminated"
    assert fed.delete_federation_job(store, "fedr",
                                     "rjob") == "routed"
    with pytest.raises(jobs_mgr.JobNotFoundError):
        jobs_mgr.get_job(store, "routed", "rjob")
    with pytest.raises(ValueError):
        fed.locate_federation_job(store, "fedr", "rjob")


# ------------------- round-4: node-level scheduling -------------------

def test_node_level_filter_and_qualifying_nodes(env):
    """Node-level constraints (reference federation.py:1939): a pool
    passes the pool filter but fails the node filter when no node has
    the required free capacity."""
    store, substrate = env
    make_pool(store, substrate, "busy", "v5litepod-4")
    make_pool(store, substrate, "free", "v5litepod-4")
    # Saturate 'busy': pretend every node is running a full slot load.
    from batch_shipyard_tpu.state import names
    for row in list(store.query_entities(names.TABLE_NODES,
                                         partition_key="busy")):
        store.merge_entity(names.TABLE_NODES, "busy", row["_rk"],
                           {"running_tasks": row.get("task_slots", 1)})
    facts = [fed._pool_facts(store, p) for p in ("busy", "free")]
    eligible = fed.filter_pools_hard_constraints(facts, {})
    assert len(eligible) == 2  # both pass the pool-level pass
    narrowed = fed.filter_pool_nodes(eligible, {})
    assert [f["pool_id"] for f in narrowed] == ["free"]
    # exclusive: node must be running NOTHING
    half = fed._pool_facts(store, "busy")
    for node in half["nodes"]:
        assert fed.qualifying_nodes(
            half, {"compute_node": {"exclusive": True}}) == []
    free_fact = fed._pool_facts(store, "free")
    assert len(fed.qualifying_nodes(
        free_fact,
        {"compute_node": {"exclusive": True}})) == free_fact[
            "nodes_total"] > 0


def test_node_constrained_job_lands_on_only_qualifying_pool(env):
    """Heterogeneous 3-pool federation: a job with node-level
    constraints lands on the single pool whose nodes qualify."""
    store, substrate = env
    from batch_shipyard_tpu.state import names
    make_pool(store, substrate, "tiny", "v5litepod-4")
    make_pool(store, substrate, "occupied", "v5litepod-16")
    make_pool(store, substrate, "roomy", "v5litepod-8")
    for row in list(store.query_entities(names.TABLE_NODES,
                                         partition_key="occupied")):
        store.merge_entity(names.TABLE_NODES, "occupied", row["_rk"],
                           {"running_tasks": row.get("task_slots", 1)})
    fed.create_federation(store, "fnode")
    for p in ("tiny", "occupied", "roomy"):
        fed.add_pool_to_federation(store, "fnode", p)
    # min_chips=8 rules out tiny; occupied is full -> roomy wins even
    # though occupied has more idle-state nodes.
    fed.submit_job_to_federation(store, "fnode", {
        "job_specifications": [{
            "id": "njob",
            "federation_constraints": {
                "min_chips": 8,
                "compute_node": {"min_free_slots": 1}},
            "tasks": [{"command": "echo node-constrained"}]}]})
    assert fed.FederationProcessor(store).process_once() == 1
    assert fed.locate_federation_job(store, "fnode", "njob") == "roomy"
    jobs_mgr.wait_for_tasks(store, "roomy", "njob", timeout=30)


def test_location_and_registry_constraints(env):
    store, substrate = env
    from batch_shipyard_tpu.agent import cascade
    from batch_shipyard_tpu.config.settings import DockerRegistry
    conf = {"pool_specification": {
        "id": "zoned", "substrate": "fake", "zone": "us-central2-b",
        "tpu": {"accelerator_type": "v5litepod-4"},
        "max_wait_time_seconds": 30}}
    pool = settings_mod.pool_settings(conf)
    pool_mgr.create_pool(store, substrate, pool, GLOBAL, conf)
    make_pool(store, substrate, "elsewhere", "v5litepod-4")
    cascade.populate_global_resources(
        store, "zoned", [], registries=[DockerRegistry(
            server="gcr.io/private", username="u",
            password="secret://env/REG_PW", auth=None)])
    facts = [fed._pool_facts(store, p) for p in ("zoned", "elsewhere")]
    by_loc = fed.filter_pools_hard_constraints(
        facts, {"location": "us-central2-b"})
    assert [f["pool_id"] for f in by_loc] == ["zoned"]
    by_reg = fed.filter_pools_hard_constraints(
        facts, {"registries": ["gcr.io/private"]})
    assert [f["pool_id"] for f in by_reg] == ["zoned"]
    assert fed.filter_pools_hard_constraints(
        facts, {"registries": ["quay.io/other"]}) == []


def test_required_target_bypasses_best_fit(env):
    """required_target pins a job to a named pool+node even when
    best-fit would pick a bigger pool (reference :2030)."""
    store, substrate = env
    make_pool(store, substrate, "small-t", "v5litepod-8")
    make_pool(store, substrate, "big-t", "v5litepod-16")
    fed.create_federation(store, "ftarget")
    fed.add_pool_to_federation(store, "ftarget", "small-t")
    fed.add_pool_to_federation(store, "ftarget", "big-t")
    fed.submit_job_to_federation(store, "ftarget", {
        "job_specifications": [{
            "id": "pinned",
            "federation_constraints": {
                "required_target": {"pool_id": "small-t",
                                    "node_id": "small-t-s0-w1"}},
            "tasks": [{"command": "echo pinned"}]}]})
    assert fed.FederationProcessor(store).process_once() == 1
    assert fed.locate_federation_job(
        store, "ftarget", "pinned") == "small-t"
    tasks = jobs_mgr.wait_for_tasks(store, "small-t", "pinned",
                                    timeout=30)
    assert tasks[0]["state"] == "completed"
    # The pin is enforced by the agents, not just preferred.
    assert tasks[0]["node_id"] == "small-t-s0-w1"
    assert tasks[0]["spec"]["required_node"] == "small-t-s0-w1"


def test_merge_action_into_existing_job_remaps_ids(env):
    """A second fed action reusing a job id appends its tasks with
    generic ids renumbered past the existing maximum (reference
    task-id collision fixup, federation.py:2605)."""
    store, substrate = env
    make_pool(store, substrate, "mergep", "v5litepod-4")
    fed.create_federation(store, "fmerge")
    fed.add_pool_to_federation(store, "fmerge", "mergep")
    fed.submit_job_to_federation(store, "fmerge", {
        "job_specifications": [{
            "id": "mj",
            "tasks": [{"command": "echo one"},
                      {"command": "echo two"}]}]})
    proc = fed.FederationProcessor(store)
    assert proc.process_once() == 1
    jobs_mgr.wait_for_tasks(store, "mergep", "mj", timeout=30)
    # Second action, same job id, colliding generic ids.
    fed.submit_job_to_federation(store, "fmerge", {
        "job_specifications": [{
            "id": "mj",
            "tasks": [{"command": "echo three"},
                      {"command": "echo four",
                       "depends_on": ["task-00000"]}]}]})
    assert proc.process_once() == 1
    tasks = jobs_mgr.wait_for_tasks(store, "mergep", "mj", timeout=30)
    ids = sorted(t["_rk"] for t in tasks)
    assert ids == ["task-00000", "task-00001", "task-00002",
                   "task-00003"]
    # The merged batch's internal depends_on was remapped: new
    # task-00003 depends on new task-00002 (which was task-00000 in
    # the incoming batch), not on the pre-existing task-00000.
    dep = next(t for t in tasks if t["_rk"] == "task-00003")
    assert dep["spec"]["depends_on"] == ["task-00002"]
    assert all(t["state"] == "completed" for t in tasks)
    # Idempotent replay: re-delivering an applied action adds nothing.
    row = store.get_entity(
        __import__("batch_shipyard_tpu.state.names",
                   fromlist=["names"]).TABLE_FEDJOBS, "fmerge", "mj")
    assert len(row["action_ids"]) == 2


def test_gc_removes_stale_placement_rows(env):
    store, substrate = env
    make_pool(store, substrate, "gcp1", "v5litepod-4")
    fed.create_federation(store, "fgc")
    fed.add_pool_to_federation(store, "fgc", "gcp1")
    fed.submit_job_to_federation(store, "fgc", {
        "job_specifications": [{
            "id": "gjob", "tasks": [{"command": "echo gc"}]}]})
    fed.FederationProcessor(store).process_once()
    jobs_mgr.wait_for_tasks(store, "gcp1", "gjob", timeout=30)
    assert fed.gc_federation_jobs(store, "fgc",
                                  grace_seconds=0.0) == []
    # Delete the job behind the federation's back -> row is stale.
    jobs_mgr.delete_job(store, "gcp1", "gjob")
    # Young rows are protected by the grace window (a GC racing the
    # scheduler's insert->add_jobs window must not reap them)...
    assert fed.gc_federation_jobs(store, "fgc") == []
    # ...but past the grace window the stale row is collected.
    assert fed.gc_federation_jobs(store, "fgc",
                                  grace_seconds=0.0) == ["gjob"]
    with pytest.raises(ValueError):
        fed.locate_federation_job(store, "fgc", "gjob")


def test_after_success_blackout_spreads_placements(env):
    """proxy_options.scheduling.after_success_blackout_interval: a
    pool that just took a job is deprioritized for the window, so
    rapid-fire submissions spread across members; with every pool
    blacked out, placement still proceeds (capacity beats
    spreading)."""
    store, substrate = env
    make_pool(store, substrate, "ba", "v5litepod-16")
    make_pool(store, substrate, "bb", "v5litepod-16")
    fed.create_federation(store, "fbo")
    fed.add_pool_to_federation(store, "fbo", "ba")
    fed.add_pool_to_federation(store, "fbo", "bb")
    proc = fed.FederationProcessor(store, after_success_blackout=60.0)
    for jid in ("j1", "j2", "j3"):
        fed.submit_job_to_federation(store, "fbo", {
            "job_specifications": [{
                "id": jid, "tasks": [{"command": "echo b"}]}]})
        assert proc.process_once() >= 1
    placements = {row["_rk"]: row["pool_id"]
                  for row in fed.list_federation_jobs(store, "fbo")}
    # First two spread across both pools; third lands despite both
    # being blacked out.
    assert len(placements) == 3
    assert set(placements.values()) == {"ba", "bb"}


def test_destroy_federation_drops_placement_and_zap_rows(env):
    store, substrate = env
    make_pool(store, substrate, "dp1", "v5litepod-4")
    fed.create_federation(store, "fdel")
    fed.add_pool_to_federation(store, "fdel", "dp1")
    fed.submit_job_to_federation(store, "fdel", {
        "job_specifications": [{
            "id": "dj", "tasks": [{"command": "echo d"}]}]})
    fed.FederationProcessor(store).process_once()
    jobs_mgr.wait_for_tasks(store, "dp1", "dj", timeout=30)
    fed.zap_action(store, "fdel", "someaction")
    from batch_shipyard_tpu.state import names
    assert list(store.query_entities(names.TABLE_FEDJOBS,
                                     partition_key="fdel"))
    fed.destroy_federation(store, "fdel")
    # Every row (placement + zap) went with the federation
    # (reference gc on destroy, convoy/storage.py:898).
    assert list(store.query_entities(names.TABLE_FEDJOBS,
                                     partition_key="fdel")) == []
    with pytest.raises(ValueError):
        fed.get_federation(store, "fdel")

#!/usr/bin/env bash
# Install batch-shipyard-tpu into a venv (reference analog: install.sh).
set -euo pipefail
VENV="${1:-.shipyard-tpu-venv}"
python3 -m venv "$VENV"
# shellcheck disable=SC1091
source "$VENV/bin/activate"
pip install --upgrade pip
pip install -e "$(cd "$(dirname "$0")" && pwd)"
echo "Installed. Activate with: source $VENV/bin/activate"
echo "Then: shipyard-tpu --help"

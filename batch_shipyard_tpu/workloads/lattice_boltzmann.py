"""CFD benchmark: the OpenFOAM recipe analog
(/root/reference/recipes/OpenFOAM-Infiniband-IntelMPI — distributed
incompressible flow), restated as a D2Q9 lattice-Boltzmann lid-driven
cavity the TPU runs as pure array ops.

The LBM update is collide (BGK relaxation, elementwise — VPU) +
stream (9 jnp.rolls — HBM bandwidth) + bounce-back walls; the whole
time loop is one lax.scan. Reports MLUPS (million lattice-site updates
per second), the standard LBM figure of merit.
"""

from __future__ import annotations

import argparse
import time

import jax
import jax.numpy as jnp
import numpy as np

from batch_shipyard_tpu.workloads import distributed

# D2Q9 lattice: velocities and weights.
_C = np.array([(0, 0), (1, 0), (0, 1), (-1, 0), (0, -1),
               (1, 1), (-1, 1), (-1, -1), (1, -1)])
_W = np.array([4 / 9] + [1 / 9] * 4 + [1 / 36] * 4)
_OPP = np.array([0, 3, 4, 1, 2, 7, 8, 5, 6])  # opposite directions


def equilibrium(rho, ux, uy):
    cu = jnp.stack([_C[i, 0] * ux + _C[i, 1] * uy for i in range(9)])
    usq = ux * ux + uy * uy
    w = jnp.asarray(_W, rho.dtype)[:, None, None]
    return w * rho[None] * (1.0 + 3.0 * cu + 4.5 * cu * cu -
                            1.5 * usq[None])


def lbm_steps(f, lid_u: float, tau: float, steps: int):
    """Run `steps` LBM updates on f: [9, H, W]."""

    inv_tau = 1.0 / tau

    def step(f, _):
        rho = jnp.sum(f, axis=0)
        ux = jnp.sum(f * jnp.asarray(_C[:, 0], f.dtype)[:, None, None],
                     axis=0) / rho
        uy = jnp.sum(f * jnp.asarray(_C[:, 1], f.dtype)[:, None, None],
                     axis=0) / rho
        feq = equilibrium(rho, ux, uy)
        f_post = f - inv_tau * (f - feq)
        # Stream: shift each population along its lattice velocity.
        f_new = jnp.stack([
            jnp.roll(jnp.roll(f_post[i], int(_C[i, 0]), axis=1),
                     int(_C[i, 1]), axis=0)
            for i in range(9)])
        # Bounce-back on the three solid walls (left/right/bottom).
        opp = f_post[jnp.asarray(_OPP)]
        wall = jnp.zeros(f.shape[1:], bool)
        wall = wall.at[0, :].set(True)     # bottom row
        wall = wall.at[:, 0].set(True)
        wall = wall.at[:, -1].set(True)
        f_new = jnp.where(wall[None], opp, f_new)
        # Moving lid (top row): Zou/He-style momentum injection.
        lid = jnp.zeros(f.shape[1:], bool).at[-1, :].set(True)
        w = jnp.asarray(_W, f.dtype)[:, None, None]
        cx = jnp.asarray(_C[:, 0], f.dtype)[:, None, None]
        lid_term = opp - 6.0 * w * rho[None] * cx * lid_u
        f_new = jnp.where(lid[None], lid_term, f_new)
        return f_new, None

    f, _ = jax.lax.scan(step, f, None, length=steps)
    return f


def main() -> int:
    parser = argparse.ArgumentParser()
    parser.add_argument("--size", type=int, default=1024,
                        help="cavity side in lattice sites")
    parser.add_argument("--steps", type=int, default=200)
    parser.add_argument("--tau", type=float, default=0.6)
    parser.add_argument("--lid-u", type=float, default=0.1)
    args = parser.parse_args()
    ctx = distributed.setup()
    h = w = args.size
    rho0 = jnp.ones((h, w), jnp.float32)
    f = equilibrium(rho0, jnp.zeros_like(rho0), jnp.zeros_like(rho0))
    run = jax.jit(lambda f: lbm_steps(f, args.lid_u, args.tau,
                                      args.steps))
    f = run(f).block_until_ready()  # warmup/compile
    start = time.perf_counter()
    f = run(f).block_until_ready()
    elapsed = time.perf_counter() - start
    mlups = h * w * args.steps / elapsed / 1e6
    rho = np.asarray(jnp.sum(f, axis=0))
    ok = np.all(np.isfinite(rho)) and abs(rho.mean() - 1.0) < 0.05
    distributed.log(ctx, (
        f"lattice_boltzmann: {h}x{w} cavity, {mlups:.1f} MLUPS, "
        f"mean density {rho.mean():.4f} "
        f"{'PASS' if ok else 'FAIL'}"))
    return 0 if ok else 1


if __name__ == "__main__":
    raise SystemExit(main())

"""Alternate-frontend payload: dm-haiku classifier training (the
Chainer/Keras+Theano recipe analog,
/root/reference/recipes/Chainer-CPU — those recipes exist to show the
scheduler is framework-agnostic; this one shows any JAX frontend runs
unchanged in the task runner, not just flax).

Usage (recipe command):
    python -m batch_shipyard_tpu.workloads.haiku_mlp --steps 200
"""

from __future__ import annotations

import argparse
import time

import haiku as hk
import jax
import jax.numpy as jnp
import numpy as np
import optax

from batch_shipyard_tpu.workloads import distributed


def main() -> int:
    parser = argparse.ArgumentParser()
    parser.add_argument("--batch", type=int, default=512)
    parser.add_argument("--features", type=int, default=256)
    parser.add_argument("--classes", type=int, default=16)
    parser.add_argument("--hidden", type=int, default=512)
    parser.add_argument("--steps", type=int, default=200)
    args = parser.parse_args()
    ctx = distributed.setup()

    def forward(x):
        mlp = hk.Sequential([
            hk.Linear(args.hidden), jax.nn.relu,
            hk.Linear(args.hidden), jax.nn.relu,
            hk.Linear(args.classes),
        ])
        return mlp(x)

    model = hk.without_apply_rng(hk.transform(forward))
    rng = np.random.RandomState(0)
    # Fixed synthetic classification problem (linearly separable-ish).
    true_w = rng.randn(args.features, args.classes)
    x = rng.randn(args.batch, args.features).astype(np.float32)
    y = np.argmax(x @ true_w + 0.1 * rng.randn(args.batch,
                                               args.classes), axis=1)
    x, y = jnp.asarray(x), jnp.asarray(y, jnp.int32)
    params = model.init(jax.random.PRNGKey(0), x)
    optimizer = optax.adam(1e-3)
    opt_state = optimizer.init(params)

    @jax.jit
    def step(params, opt_state):
        def loss_fn(p):
            logits = model.apply(p, x)
            onehot = jax.nn.one_hot(y, args.classes)
            return -jnp.mean(jnp.sum(
                onehot * jax.nn.log_softmax(logits), axis=-1))

        loss, grads = jax.value_and_grad(loss_fn)(params)
        updates, opt_state = optimizer.update(grads, opt_state)
        return optax.apply_updates(params, updates), opt_state, loss

    start = time.perf_counter()
    for i in range(args.steps):
        params, opt_state, loss = step(params, opt_state)
    loss = float(loss)
    elapsed = time.perf_counter() - start
    logits = model.apply(params, x)
    acc = float(jnp.mean(jnp.argmax(logits, axis=-1) == y))
    distributed.log(ctx, (
        f"haiku_mlp: {args.steps} steps in {elapsed:.1f}s, "
        f"loss={loss:.4f}, train acc={acc:.3f} "
        f"{'PASS' if acc > 0.8 else 'FAIL'}"))
    return 0 if acc > 0.8 else 1


if __name__ == "__main__":
    raise SystemExit(main())

"""int8 KV cache (kv_cache_dtype='int8'): half the HBM per cached
token. Accuracy vs the fp cache (logits within quantization noise),
storage dtype actually int8, generation + continuous-batching engine
end-to-end, and the paged-combination guard."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from batch_shipyard_tpu.models import inference as inf
from batch_shipyard_tpu.models import serving
from batch_shipyard_tpu.models import transformer as tfm

CFG = tfm.TransformerConfig(
    vocab_size=97, d_model=64, n_layers=2, n_heads=4, d_head=16,
    d_ff=128, max_seq_len=64, dtype=jnp.float32,
    param_dtype=jnp.float32)


@pytest.fixture(scope="module")
def params():
    return tfm.TransformerLM(CFG).init(
        jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32))["params"]


def _decode_model(kv_dtype):
    cfg = dataclasses.replace(
        inf.decode_config(CFG, 64), kv_cache_dtype=kv_dtype)
    return tfm.TransformerLM(cfg)


def test_cache_leaves_are_int8_with_scales(params):
    model = _decode_model("int8")
    cache = inf.init_cache(model, params, batch_size=2)
    leaves = {}
    for path, leaf in jax.tree_util.tree_leaves_with_path(cache):
        leaves[path[-1].key] = leaf
    assert leaves["k"].dtype == jnp.int8
    assert leaves["v"].dtype == jnp.int8
    assert leaves["k_scale"].dtype == jnp.float32
    assert leaves["v_scale"].dtype == jnp.float32
    # Capacity claim measured on the ACTUAL arrays: int8 K + its
    # scales must be under half of what the fp cache stores.
    fp_model = _decode_model(None)
    fp_cache = inf.init_cache(fp_model, params, batch_size=2)
    fp_k = [leaf for path, leaf in
            jax.tree_util.tree_leaves_with_path(fp_cache)
            if path[-1].key == "k"]
    int8_bytes = leaves["k"].nbytes + leaves["k_scale"].nbytes
    assert int8_bytes <= fp_k[0].nbytes // 2


def test_int8_logits_within_quantization_noise(params):
    """Single-step decode logits with the int8 cache stay within
    ~2% relative of the fp cache's."""
    prompt = jnp.asarray([[5, 17, 31, 2, 9, 40]], jnp.int32)

    def last_logits(kv_dtype):
        model = _decode_model(kv_dtype)
        cache = inf.init_cache(model, params, 1)
        hidden, _ = model.apply(
            {"params": params, "cache": cache}, prompt,
            return_hidden=True, mutable=["cache"])
        emb = params["embed"]["embedding"]
        return jnp.dot(hidden[:, -1].astype(jnp.float32),
                       emb.astype(jnp.float32).T)

    ref = last_logits(None)
    got = last_logits("int8")
    rel = (np.linalg.norm(np.asarray(got - ref)) /
           np.linalg.norm(np.asarray(ref)))
    assert rel < 0.02, rel


def test_int8_generation_runs_and_mostly_agrees(params):
    """Full 24-token greedy generation with the int8 cache: tokens
    stay in-vocab and agree with the fp run for a long prefix (the
    divergence point, if any, is an argmax near-tie under
    quantization noise)."""
    prompt = jnp.asarray([[5, 17, 31, 2], [9, 9, 1, 42]], jnp.int32)

    def run(kv_dtype):
        model = _decode_model(kv_dtype)
        cache = inf.init_cache(model, params, prompt.shape[0])
        tokens, _ = inf.generate(model, params, cache, prompt, 24,
                                 jax.random.PRNGKey(0))
        return np.asarray(tokens)

    ref, got = run(None), run("int8")
    assert got.shape == ref.shape
    assert (got >= 0).all() and (got < CFG.vocab_size).all()
    agree = int((got == ref).all(axis=0).sum())
    assert agree >= ref.shape[1] // 2, (agree, ref.shape[1])


def test_int8_serving_engine_end_to_end(params):
    """ContinuousBatcher on the int8 cache: requests complete with
    in-vocab tokens through admit/decode/finish."""
    cfg = dataclasses.replace(CFG, kv_cache_dtype="int8")
    engine = serving.ContinuousBatcher(cfg, params, num_slots=2,
                                       max_decode_len=64)
    for i in range(3):
        engine.submit(serving.Request(f"r{i}", [3 + i, 7, 11],
                                      max_new_tokens=6))
    done = {}
    while engine.pending():
        for rid, tokens in engine.step():
            done[rid] = tokens
    assert set(done) == {"r0", "r1", "r2"}
    assert all(len(t) == 6 for t in done.values())
    assert all(0 <= tok < CFG.vocab_size
               for t in done.values() for tok in t)


def test_int8_paged_pool_leaves_and_engine(params):
    """int8 PAGED pool: pages stored int8 with per-row scale pages;
    the continuous batcher (incl. overcommit preemption machinery)
    runs end-to-end on it."""
    cfg = dataclasses.replace(CFG, kv_cache_dtype="int8")
    engine = serving.ContinuousBatcher(
        cfg, params, num_slots=2, max_decode_len=64,
        kv_page_size=16, overcommit=True)
    leaves = {}
    for path, leaf in jax.tree_util.tree_leaves_with_path(
            engine.cache):
        leaves[path[-1].key] = leaf
    assert leaves["k_pages"].dtype == jnp.int8
    assert leaves["v_pages"].dtype == jnp.int8
    assert leaves["k_page_scales"].dtype == jnp.float32
    assert leaves["k_page_scales"].shape == \
        leaves["k_pages"].shape[:3]
    for i in range(3):
        engine.submit(serving.Request(f"p{i}", [3 + i, 7, 11],
                                      max_new_tokens=6))
    done = {}
    while engine.pending():
        for rid, tokens in engine.step():
            done[rid] = tokens
    assert set(done) == {"p0", "p1", "p2"}
    assert all(len(t) == 6 for t in done.values())
    assert all(0 <= tok < CFG.vocab_size
               for t in done.values() for tok in t)


def test_int8_paged_tokens_close_to_fp_paged(params):
    """Same prompts through fp and int8 paged engines: outputs agree
    for a long prefix (divergence only at argmax near-ties under
    quantization noise)."""
    def run(kv_dtype):
        cfg = dataclasses.replace(CFG, kv_cache_dtype=kv_dtype)
        engine = serving.ContinuousBatcher(
            cfg, params, num_slots=2, max_decode_len=64,
            kv_page_size=16)
        engine.submit(serving.Request("r", [5, 17, 31, 2],
                                      max_new_tokens=16))
        out = None
        while engine.pending():
            for _rid, tokens in engine.step():
                out = tokens
        return out

    ref, got = run(None), run("int8")
    agree = 0
    for a, b in zip(ref, got):
        if a != b:
            break
        agree += 1
    assert agree >= len(ref) // 2, (agree, ref, got)


def test_unknown_kv_cache_dtype_rejected(params):
    cfg = dataclasses.replace(inf.decode_config(CFG, 64),
                              kv_cache_dtype="fp8")
    model = tfm.TransformerLM(cfg)
    with pytest.raises(ValueError):
        inf.init_cache(model, params, 1)


def test_int8_kv_dequant_fusion_check_runs():
    """tools/tpu_checks.check_int8_kv_dequant_fusion (ADVICE r5): the
    check must compile the dense int8 decode step and return a
    verdict on every backend. The PASS threshold is a silicon
    question (CPU XLA is known to materialize the dequantized cache);
    here we pin that the measurement itself works and the threshold
    is the documented one-dequantized-cache footprint."""
    import pathlib
    import sys
    repo_root = str(pathlib.Path(__file__).resolve().parent.parent)
    if repo_root not in sys.path:
        sys.path.insert(0, repo_root)
    from tools.tpu_checks import check_int8_kv_dequant_fusion
    assert isinstance(check_int8_kv_dequant_fusion(), bool)

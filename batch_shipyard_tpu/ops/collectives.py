"""Collective microbenchmarks: the mpiBench/OSU recipe analog.

The reference ships MPI microbenchmark recipes (mpiBench-OpenMPI, OSU)
that exercise the Infiniband fabric; on TPU the fabric is ICI/DCN and
the collectives are XLA's (psum, all_gather, ppermute, reduce_scatter)
reached through shard_map. These functions time them per message size
and report bus bandwidth, runnable identically on a real pod slice or
the virtual CPU mesh.
"""

from __future__ import annotations

import functools
import time
from typing import Callable, Iterable

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from batch_shipyard_tpu.utils.compat import shard_map


def _timeit(fn: Callable, arg, warmup: int = 2, iters: int = 10) -> float:
    for _ in range(warmup):
        jax.block_until_ready(fn(arg))
    start = time.perf_counter()
    for _ in range(iters):
        out = fn(arg)
    jax.block_until_ready(out)
    return (time.perf_counter() - start) / iters


def _collective_fn(mesh: Mesh, axis: str, op: str) -> Callable:
    if op == "psum":
        def inner(x):
            return jax.lax.psum(x, axis)
    elif op == "all_gather":
        def inner(x):
            return jax.lax.all_gather(x, axis)
    elif op == "reduce_scatter":
        def inner(x):
            return jax.lax.psum_scatter(x, axis, tiled=True)
    elif op == "ppermute":
        size = mesh.shape[axis]

        def inner(x):
            return jax.lax.ppermute(
                x, axis, [(i, (i + 1) % size) for i in range(size)])
    else:
        raise ValueError(f"unknown collective {op!r}")
    # (in_spec, out_spec) per op: inputs are sharded over the axis;
    # psum and all_gather produce replicated outputs.
    specs = {
        "psum": (P(axis), P(None)),
        "all_gather": (P(axis), P(None)),
        "reduce_scatter": (P(axis), P(axis)),
        "ppermute": (P(axis), P(axis)),
    }
    in_spec, out_spec = specs[op]
    return jax.jit(shard_map(inner, mesh=mesh, in_specs=in_spec,
                             out_specs=out_spec, check_vma=False))


def run_collective_bench(
        mesh: Mesh, axis: str = "dp",
        ops: Iterable[str] = ("psum", "all_gather", "ppermute",
                              "reduce_scatter"),
        sizes_bytes: Iterable[int] = (1 << 16, 1 << 20, 1 << 24),
        dtype=jnp.bfloat16) -> list[dict]:
    """Time each collective per message size; returns rows of
    {op, bytes, seconds, algo_bw_gbps, bus_bw_gbps}."""
    n = mesh.shape[axis]
    results = []
    itemsize = jnp.dtype(dtype).itemsize
    for op in ops:
        fn = _collective_fn(mesh, axis, op)
        for size in sizes_bytes:
            elems = max(n * 128, size // itemsize)
            elems -= elems % (n * 128)
            x = jnp.ones((elems,), dtype=dtype)
            seconds = _timeit(fn, x)
            nbytes = elems * itemsize
            algo_bw = nbytes / seconds / 1e9
            # Bus-bandwidth correction factors (NCCL-tests convention).
            if op == "psum":
                factor = 2 * (n - 1) / n
            elif op in ("all_gather", "reduce_scatter"):
                factor = (n - 1) / n
            else:
                factor = 1.0
            results.append({
                "op": op, "bytes": nbytes, "seconds": seconds,
                "algo_bw_gbps": algo_bw,
                "bus_bw_gbps": algo_bw * factor,
            })
    return results


@functools.partial(jax.jit, static_argnames=("axis",))
def psum_latency_probe(x, axis: str = "dp"):
    """Minimal-size psum for latency measurement (OSU latency analog).
    Call under shard_map or pjit with x sharded over axis."""
    return jax.lax.psum(x, axis)


def hierarchical_all_to_all(x, outer_axis: str, inner_axis: str):
    """Two-phase all-to-all over a factored device axis: ICI first,
    then DCN — the expert-parallel dispatch primitive when experts
    span slices.

    Call inside shard_map on a mesh where the expert axis is factored
    as (outer_axis, inner_axis) — outer across slices (DCN), inner
    within a slice (ICI). ``x`` is DESTINATION-indexed per device:
    shape [n_out, n_in, ...] where x[o', i'] is the block this device
    sends to device (o', i'). Returns the SOURCE-indexed gather:
    y[o, i] = block sent to this device by device (o, i).

    Why not one all_to_all over the combined axis: that sends each
    (src, dst) block as its own DCN message — n_in^2 small messages
    per slice pair. Phase 1 (inner axis, ICI) routes blocks to the
    slice-mate whose inner rank matches the destination's; phase 2
    (outer axis, DCN) then moves ONE aggregated [n_in, ...] message
    per slice pair — n_in-fold fewer, n_in-fold bigger DCN transfers,
    which is the win on a latency-dominated cross-slice fabric.

    Phase algebra (device (o, i), A = phase-1 result, B = result):
      A[d_o, s_i] = x_{(o, s_i)}[d_o, i]      (a2a over inner, dim 1)
      B[s_o, s_i] = A_{(s_o, i)}[o, s_i]
                  = x_{(s_o, s_i)}[o, i]      (a2a over outer, dim 0)

    Reference analog: none (SURVEY.md 5.8 net-new); the factored
    exchange follows the standard hierarchical/2D all-to-all scheme
    used by MoE systems (PAPERS.md).
    """
    x = jax.lax.all_to_all(x, inner_axis, split_axis=1,
                           concat_axis=1)
    return jax.lax.all_to_all(x, outer_axis, split_axis=0,
                              concat_axis=0)

"""Span-kind registry + span recorders (store-backed and
process-local).

The twin of goodput/events.py, but identity-first: every span carries
(trace_id, span_id, parent_span_id) so export.py can rebuild the
causal chain of one submission. Two producer surfaces feed one log:

  * **Store-backed** (`emit` / `span` / `query`): components holding a
    StateStore handle — the jobs manager (submit span), the node agent
    (claim/backoff/requeue/rendezvous/run/cache-seed spans). Spans
    land in TABLE_TRACE partitioned by pool.
  * **Process-local** (`record` / `phase`): workload code inside a
    task subprocess appends JSONL to $SHIPYARD_TRACE_FILE; the agent
    ingests the file post-task with the task's identity attached
    (`ingest_local_spans`), exactly like the goodput recorder. The
    trace/parent ids default to the task context the agent exported
    ($SHIPYARD_TRACE_* — context.TraceContext.from_env), so program
    spans parent under the task's run span with zero plumbing in the
    workloads. With no sink configured the recorder is a no-op.

Span dict schema (what export.py consumes)::

    {"kind": str, "trace_id": str, "span_id": str,
     "parent_span_id": Optional[str], "start": float, "end": float,
     "pool_id"/"job_id"/"task_id"/"node_id": Optional[str],
     "attrs": {...}}

Every kind emitted anywhere must be declared here: the registry is
enforced by an AST scan in tests/test_names_consistency.py, so a
typo'd kind cannot silently produce spans the export drops. Emission
is best-effort by design — a failed trace write must never fail the
work being traced.
"""

from __future__ import annotations

import contextlib
import json
import os
import time
import uuid
from typing import Any, Iterator, Optional

from batch_shipyard_tpu.state import names
from batch_shipyard_tpu.state.base import StateStore
from batch_shipyard_tpu.trace import context as trace_ctx
from batch_shipyard_tpu.utils import util

logger = util.get_logger(__name__)

# ----------------------------- span kinds ------------------------------

# Submission / scheduling (store-backed emitters)
SPAN_SUBMIT = "submit"                   # jobs add -> entities+queued
SPAN_QUEUE_WAIT = "queue_wait"           # submit/requeue -> claim
SPAN_CLAIM = "claim"                     # instantaneous claim marker
SPAN_BACKOFF_WAIT = "backoff_wait"       # retry supervisor delay
SPAN_REQUEUE = "requeue"                 # instantaneous requeue marker
SPAN_RENDEZVOUS = "gang_rendezvous"      # gang join -> full formation
SPAN_IMAGE_PULL = "image_pull"           # image provisioning on node
SPAN_TASK_RUN = "task_run"               # task process start -> exit
SPAN_CACHE_SEED = "compile_cache_seed"   # pre-task pool-cache seed
SPAN_PREEMPT = "preempt"                 # preempt notice -> drained
                                         # exit (cooperative window)
SPAN_EVICT = "evict"                     # preempt notice -> hard
                                         # kill (the escalation
                                         # window a victim burned by
                                         # ignoring its notice)
SPAN_GANG_RESIZE = "gang_resize"         # instantaneous: broken gang
                                         # re-formed at a new size
SPAN_AGENT_RESTART = "agent_restart"     # crashed agent's last
#                                          heartbeat -> restarted
#                                          agent re-adopted the
#                                          still-running task (the
#                                          crash-restart adoption
#                                          recovery leg)
SPAN_GANG_MIGRATE = "gang_migrate"       # starved in source pool ->
                                         # re-targeted on the sibling
                                         # pool (one trace spans the
                                         # migration)

# Program phases (process-local emitters inside the task)
SPAN_COMPILE = "compile"                 # jit warm-up / AOT precompile
SPAN_STEP_WINDOW = "train_step_window"   # productive step run
SPAN_CKPT_SNAPSHOT = "checkpoint_snapshot"   # step-boundary device_get
SPAN_CKPT_PERSIST = "checkpoint_persist"     # write-out (sync or
                                             # overlapped; attrs carry
                                             # overlapped=True/False)
SPAN_CKPT_RESTORE = "checkpoint_restore"
SPAN_PROFILE = "profile"                 # jax.profiler capture window

# Serving per-request spans (recorded by the front end)
SPAN_SERVE_REQUEST = "serve_request"     # admit -> completion (parent)
SPAN_SERVE_QUEUED = "serve_queued"       # submit -> engine admission
SPAN_SERVE_PREFILL = "serve_prefill"     # admission -> first token
SPAN_SERVE_DECODE = "serve_decode"       # first token -> last token;
                                         # speculative accept/rewind
                                         # counters annotated in attrs

SPAN_KINDS = frozenset({
    SPAN_SUBMIT, SPAN_QUEUE_WAIT, SPAN_CLAIM, SPAN_BACKOFF_WAIT,
    SPAN_REQUEUE, SPAN_RENDEZVOUS, SPAN_IMAGE_PULL, SPAN_TASK_RUN,
    SPAN_CACHE_SEED, SPAN_PREEMPT, SPAN_EVICT, SPAN_GANG_RESIZE,
    SPAN_GANG_MIGRATE, SPAN_AGENT_RESTART,
    SPAN_COMPILE, SPAN_STEP_WINDOW, SPAN_CKPT_SNAPSHOT,
    SPAN_CKPT_PERSIST, SPAN_CKPT_RESTORE, SPAN_PROFILE,
    SPAN_SERVE_REQUEST, SPAN_SERVE_QUEUED, SPAN_SERVE_PREFILL,
    SPAN_SERVE_DECODE,
})


# ----------------------------- store-backed ----------------------------

def emit(store: StateStore, pool_id: str, kind: str,
         ctx: Optional[trace_ctx.TraceContext], *,
         job_id: Optional[str] = None, task_id: Optional[str] = None,
         node_id: Optional[str] = None,
         start: Optional[float] = None, end: Optional[float] = None,
         attrs: Optional[dict] = None,
         self_span: bool = False) -> Optional[str]:
    """Append one span under ``ctx`` (a NEW child span id is minted;
    the span's parent is ctx.span_id). ``self_span=True`` instead
    records ctx's OWN span (id = ctx.span_id, parent =
    ctx.parent_span_id) — used for spans whose id must be known in
    advance, like the submit root every task row parents under. No-op
    for ctx=None (legacy untraced tasks) or an undeclared kind.
    Returns the span id written, or None when nothing was. Never
    raises: tracing is an observer, not a participant."""
    if ctx is None:
        return None
    if kind not in SPAN_KINDS:
        logger.warning("unknown span kind %r dropped", kind)
        return None
    if self_span:
        span_id, parent = ctx.span_id, ctx.parent_span_id
    else:
        span_id, parent = trace_ctx.new_span_id(), ctx.span_id
    try:
        ts = time.time() if start is None else float(start)
        entity = {
            "kind": kind, "trace_id": ctx.trace_id,
            "span_id": span_id, "parent_span_id": parent,
            "job_id": job_id, "task_id": task_id, "node_id": node_id,
            "start": ts, "end": ts if end is None else float(end),
            "attrs": dict(attrs or {}),
        }
        row_key = f"{ts:017.6f}${uuid.uuid4().hex[:8]}"
        store.insert_entity(names.TABLE_TRACE, pool_id, row_key,
                            entity)
        return span_id
    except Exception:  # noqa: BLE001 - observer must not fail work
        logger.debug("trace emit failed", exc_info=True)
        return None


@contextlib.contextmanager
def span(store: StateStore, pool_id: str, kind: str,
         ctx: Optional[trace_ctx.TraceContext], *,
         job_id: Optional[str] = None, task_id: Optional[str] = None,
         node_id: Optional[str] = None,
         attrs: Optional[dict] = None) -> Iterator[dict]:
    """Time a block as one span; yields the attrs dict so the body
    can add counters before the span is emitted."""
    out_attrs = dict(attrs or {})
    start = time.time()
    try:
        yield out_attrs
    finally:
        emit(store, pool_id, kind, ctx, job_id=job_id, task_id=task_id,
             node_id=node_id, start=start, end=time.time(),
             attrs=out_attrs)


def query(store: StateStore, pool_id: str,
          trace_id: Optional[str] = None,
          job_id: Optional[str] = None) -> list[dict]:
    """Spans of a pool (optionally one trace/job), sorted by start."""
    out = []
    for row in store.query_entities(names.TABLE_TRACE,
                                    partition_key=pool_id):
        if trace_id is not None and row.get("trace_id") != trace_id:
            continue
        if job_id is not None and row.get("job_id") != job_id:
            continue
        out.append(row)
    return sorted(out, key=lambda e: (e.get("start", 0.0),
                                      e.get("end", 0.0)))


def prune(store: StateStore, pool_id: str,
          older_than_seconds: float) -> int:
    """Retention sweep (the goodput-log rule): drop spans that ENDED
    more than ``older_than_seconds`` ago."""
    cutoff = time.time() - older_than_seconds
    removed = 0
    for row in list(store.query_entities(names.TABLE_TRACE,
                                         partition_key=pool_id)):
        if float(row.get("end", row.get("start", 0.0))) < cutoff:
            try:
                store.delete_entity(names.TABLE_TRACE, pool_id,
                                    row["_rk"])
                removed += 1
            except Exception:  # noqa: BLE001 - best effort
                logger.debug("trace prune failed", exc_info=True)
    return removed


# ---------------------------- process-local ----------------------------

def local_spans_path() -> Optional[str]:
    """The JSONL sink for THIS process, or None (recorder disabled)."""
    return os.environ.get(trace_ctx.TRACE_FILE_ENV) or None


def record(kind: str, start: float, end: Optional[float] = None,
           parent_span_id: Optional[str] = None,
           span_id: Optional[str] = None,
           **attrs: Any) -> Optional[str]:
    """Process-local emit: append one JSONL span to
    $SHIPYARD_TRACE_FILE. The trace id comes from the task context the
    agent exported; ``parent_span_id`` defaults to the task's own span
    (the run span), so flat program phases chain correctly with no
    caller plumbing. No-op when no sink or no context is configured;
    never raises. Returns the span id written (for parenting child
    spans), or None."""
    return _record(kind, start, end, attrs,
                   parent_span_id=parent_span_id, span_id=span_id)


def _record(kind: str, start: float, end: Optional[float],
            attrs: dict,
            parent_span_id: Optional[str] = None,
            span_id: Optional[str] = None) -> Optional[str]:
    """Dict-attrs core of record(): attr keys can never collide with
    the positional parameters (a phase() body writing
    attrs["start"]/["end"] must degrade to data, not raise a
    TypeError out of the finally block into the traced work)."""
    path = local_spans_path()
    ctx = trace_ctx.TraceContext.from_env()
    if path is None or ctx is None:
        return None
    if kind not in SPAN_KINDS:
        logger.warning("unknown span kind %r dropped", kind)
        return None
    sid = span_id or trace_ctx.new_span_id()
    event = {
        "kind": kind, "trace_id": ctx.trace_id, "span_id": sid,
        "parent_span_id": parent_span_id or ctx.span_id,
        "start": float(start),
        "end": float(start if end is None else end),
        "attrs": dict(attrs),
    }
    try:
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        with open(path, "a", encoding="utf-8") as fh:
            fh.write(json.dumps(event) + "\n")
        return sid
    except OSError:
        logger.debug("trace local record failed", exc_info=True)
        return None


@contextlib.contextmanager
def phase(kind: str, **attrs: Any) -> Iterator[dict]:
    """Time a block as a process-local span; yields the attrs dict
    (mutable — counters get filled in by the body; any key is safe,
    including "start"/"end")."""
    out_attrs = dict(attrs)
    start = time.time()
    try:
        yield out_attrs
    finally:
        _record(kind, start, time.time(), out_attrs)


def ingest_local_spans(store: StateStore, pool_id: str, path: str, *,
                       job_id: Optional[str] = None,
                       task_id: Optional[str] = None,
                       node_id: Optional[str] = None) -> int:
    """Fold a task's process-local JSONL spans into the store with the
    task's identity attached. The file's contents are task-controlled:
    junk lines are skipped, never raised into the agent's task flow.
    The file is removed on success so retries don't double-count."""
    if not os.path.exists(path):
        return 0
    count = 0
    rows: list[tuple[str, str, dict]] = []
    try:
        with open(path, encoding="utf-8") as fh:
            for line in fh:
                line = line.strip()
                if not line:
                    continue
                try:
                    event = json.loads(line)
                except ValueError:
                    continue
                if not isinstance(event, dict) or \
                        event.get("kind") not in SPAN_KINDS:
                    continue
                trace_id = event.get("trace_id")
                span_id = event.get("span_id")
                if not trace_id or not span_id:
                    continue
                try:
                    start = float(event.get("start"))
                    end = float(event.get("end", start))
                except (TypeError, ValueError):
                    continue
                attrs = event.get("attrs")
                if not isinstance(attrs, dict):
                    attrs = {}
                row_key = f"{start:017.6f}${uuid.uuid4().hex[:8]}"
                rows.append((pool_id, row_key, {
                    "kind": event["kind"],
                    "trace_id": str(trace_id),
                    "span_id": str(span_id),
                    "parent_span_id": event.get("parent_span_id"),
                    "job_id": job_id, "task_id": task_id,
                    "node_id": node_id,
                    "start": start, "end": end,
                    "attrs": attrs,
                }))
        # One batched insert for the whole file (a task can emit
        # thousands of spans; per-row writes made ingestion a
        # round-trip storm on the heartbeat path). Best effort with
        # the same loss-over-duplication bias as the old per-row
        # loop: the file is removed either way, so a partial batch
        # failure drops spans rather than double-counting them on
        # the next ingest pass.
        try:
            store.insert_entities(names.TABLE_TRACE, rows)
            count = len(rows)
        except Exception:  # noqa: BLE001 - best effort
            logger.debug("trace ingest insert failed", exc_info=True)
        os.remove(path)
    except OSError:
        logger.debug("trace ingest failed for %s", path, exc_info=True)
    return count

#!/bin/bash
# Periodic TPU-availability probe + bench runner (VERDICT r2 order #1:
# "retry periodically — do not leave the bench to the end-of-round
# snapshot"). Loops until the accelerator answers, logging every
# attempt to BENCH_ATTEMPTS.log; on success runs tools/tpu_checks.py
# and bench.py and exits.
cd /root/repo || exit 1
LOG=BENCH_ATTEMPTS.log
while true; do
    TS=$(date -u +%Y-%m-%dT%H:%M:%SZ)
    timeout 300 python - <<'EOF' > /tmp/probe_out.txt 2>&1
import jax, jax.numpy as jnp
x = jnp.ones((256, 256), jnp.bfloat16)
(x @ x).block_until_ready()
print("OK", jax.devices())
EOF
    RC=$?
    if [ $RC -eq 0 ] && grep -q '^OK' /tmp/probe_out.txt; then
        echo "$TS probe OK — running tpu_checks + bench" >> "$LOG"
        timeout 1800 python tools/tpu_checks.py \
            > TPU_CHECKS_r04.txt 2>&1
        echo "$TS tpu_checks rc=$?" >> "$LOG"
        timeout 1800 python bench.py > /tmp/bench_out.txt 2>&1
        BRC=$?
        if [ $BRC -eq 0 ]; then
            tail -1 /tmp/bench_out.txt > BENCH_LATEST.json
        fi
        echo "$TS bench rc=$BRC: $(tail -1 /tmp/bench_out.txt)" \
            >> "$LOG"
        exit 0
    fi
    echo "$TS probe FAILED rc=$RC: $(tail -1 /tmp/probe_out.txt)" \
        >> "$LOG"
    sleep 600
done

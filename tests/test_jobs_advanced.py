"""Advanced job features: merge tasks, schedules/recurrence, migrate,
disable/enable, cross-task input data."""

import json
import time

import pytest

from batch_shipyard_tpu.config import settings as settings_mod
from batch_shipyard_tpu.jobs import manager as jobs_mgr
from batch_shipyard_tpu.jobs import schedules
from batch_shipyard_tpu.pool import manager as pool_mgr
from batch_shipyard_tpu.state import names
from batch_shipyard_tpu.state.memory import MemoryStateStore
from batch_shipyard_tpu.substrate.fakepod import FakePodSubstrate

GLOBAL = settings_mod.global_settings({})


def make_env(pool_id="pool1"):
    conf = {"pool_specification": {
        "id": pool_id, "substrate": "fake",
        "tpu": {"accelerator_type": "v5litepod-16"},
        "max_wait_time_seconds": 30,
    }}
    store = MemoryStateStore()
    substrate = FakePodSubstrate(store)
    pool = settings_mod.pool_settings(conf)
    pool_mgr.create_pool(store, substrate, pool, GLOBAL, conf)
    return store, substrate, pool


def test_merge_task_runs_last():
    store, substrate, pool = make_env()
    try:
        jobs = settings_mod.job_settings_list({"job_specifications": [{
            "id": "jm",
            "tasks": [
                {"id": "a", "command": "echo a"},
                {"id": "b", "command": "echo b"},
            ],
            "merge_task": {"id": "merge", "command": "echo merged"},
        }]})
        counts = jobs_mgr.add_jobs(store, pool, jobs)
        assert counts["jm"] == 3
        tasks = {t["_rk"]: t for t in jobs_mgr.wait_for_tasks(
            store, "pool1", "jm", timeout=30)}
        assert tasks["merge"]["state"] == "completed"
        assert tasks["merge"]["started_at"] >= tasks["a"]["completed_at"]
        assert tasks["merge"]["started_at"] >= tasks["b"]["completed_at"]
    finally:
        substrate.stop_all()


def test_task_output_input_data_cross_task():
    store, substrate, pool = make_env()
    try:
        jobs = settings_mod.job_settings_list({"job_specifications": [{
            "id": "jx",
            "tasks": [
                {"id": "producer",
                 "command": "echo payload > result.txt",
                 "output_data": [{"include": "*.txt"}]},
                {"id": "consumer",
                 "command": "cat producer/result.txt",
                 "depends_on": ["producer"],
                 "input_data": [{"kind": "task_output",
                                 "task_id": "producer"}]},
            ],
        }]})
        jobs_mgr.add_jobs(store, pool, jobs)
        tasks = {t["_rk"]: t for t in jobs_mgr.wait_for_tasks(
            store, "pool1", "jx", timeout=30)}
        assert tasks["consumer"]["state"] == "completed"
        out = jobs_mgr.get_task_output(store, "pool1", "jx", "consumer")
        assert out.strip() == b"payload"
    finally:
        substrate.stop_all()


def test_disable_enable_job():
    store, substrate, pool = make_env()
    try:
        # Seed the job already-disabled (deterministic: no race with
        # agents picking the task up before disable lands).
        store.insert_entity(names.TABLE_JOBS, "pool1", "jd",
                            {"state": "disabled", "spec": {}})
        store.insert_entity(
            names.TABLE_TASKS, names.task_pk("pool1", "jd"),
            "task-00000", {"state": "pending", "retries": 0,
                           "spec": {"command": "echo hi",
                                    "runtime": "none"}})
        store.put_message(names.task_queue("pool1"), json.dumps(
            {"job_id": "jd", "task_id": "task-00000"}).encode())
        time.sleep(1.0)
        task = jobs_mgr.get_task(store, "pool1", "jd", "task-00000")
        assert task["state"] == "pending"  # not scheduled while disabled
        jobs_mgr.enable_job(store, "pool1", "jd")
        tasks = jobs_mgr.wait_for_tasks(store, "pool1", "jd", timeout=30)
        assert tasks[0]["state"] == "completed"
    finally:
        substrate.stop_all()


def test_migrate_job_between_pools():
    store = MemoryStateStore()
    substrate = FakePodSubstrate(store)
    try:
        conf1 = {"pool_specification": {
            "id": "src", "substrate": "fake",
            "tpu": {"accelerator_type": "v5litepod-4"},
            "max_wait_time_seconds": 30}}
        conf2 = {"pool_specification": {
            "id": "dst", "substrate": "fake",
            "tpu": {"accelerator_type": "v5litepod-4"},
            "max_wait_time_seconds": 30}}
        src = settings_mod.pool_settings(conf1)
        dst = settings_mod.pool_settings(conf2)
        pool_mgr.create_pool(store, substrate, dst, GLOBAL, conf2)
        # Source pool never allocated: its tasks stay pending.
        store.insert_entity(names.TABLE_POOLS, "pools", "src",
                            {"state": "ready", "spec": {}})
        jobs = settings_mod.job_settings_list({"job_specifications": [{
            "id": "jmig", "tasks": [{"command": "echo migrated"}]}]})
        jobs_mgr.add_jobs(store, src, jobs)
        # Active jobs must be disabled first.
        with pytest.raises(RuntimeError):
            jobs_mgr.migrate_job(store, "src", "jmig", "dst")
        jobs_mgr.disable_job(store, "src", "jmig")
        with pytest.raises(ValueError):
            jobs_mgr.migrate_job(store, "src", "jmig", "nopool")
        moved = jobs_mgr.migrate_job(store, "src", "jmig", "dst")
        assert moved == 1
        jobs_mgr.enable_job(store, "dst", "jmig")
        with pytest.raises(jobs_mgr.JobNotFoundError):
            jobs_mgr.get_job(store, "src", "jmig")
        tasks = jobs_mgr.wait_for_tasks(store, "dst", "jmig",
                                        timeout=30)
        assert tasks[0]["state"] == "completed"
    finally:
        substrate.stop_all()


class _StaleScheduleReadStore:
    """Proxy store replaying a CANNED read of the schedule row — the
    deterministic form of a concurrent evaluator that snapshotted
    state before the other evaluator wrote. Every other operation
    (including the claim write) hits the live store."""

    def __init__(self, store, stale_entity):
        self._store = store
        self._stale = stale_entity

    def get_entity(self, table, pk, rk):
        if table == names.TABLE_JOBSCHEDULES:
            from batch_shipyard_tpu.state.base import NotFoundError
            if self._stale is None:
                raise NotFoundError(f"{table}:{pk}:{rk}")
            return dict(self._stale)
        return self._store.get_entity(table, pk, rk)

    def __getattr__(self, name):
        return getattr(self._store, name)


def test_schedule_concurrent_evaluators_launch_once():
    """Regression (PR 11, found by shipyard lint's
    store-blind-upsert): two schedule evaluators racing on one
    recurrence — both read run_number=N before either writes — must
    launch exactly ONE instance. The loser's claim hits
    EntityExistsError (first run) or EtagMismatchError (later runs)
    and skips; the old blind upsert let both launch instance N."""
    store, substrate, pool = make_env()
    try:
        jobs = settings_mod.job_settings_list({"job_specifications": [{
            "id": "race",
            "recurrence": {"schedule": {
                "recurrence_interval_seconds": 1}},
            "tasks": [{"command": "echo tick"}],
        }]})
        t0 = time.time()
        # Evaluator A wins the first recurrence.
        assert schedules.run_due_schedules(
            store, pool, jobs, now=t0) == ["race-r00000"]
        # Evaluator B read BEFORE A wrote (no row yet): its
        # insert-claim must collide and skip — no duplicate
        # race-r00000 submission, no exception.
        stale = _StaleScheduleReadStore(store, None)
        assert schedules.run_due_schedules(
            stale, pool, jobs, now=t0) == []
        # Later recurrence: A launches r00001; B holds the row as it
        # was BEFORE (run_number=1, stale etag) — its etag-guarded
        # merge must lose, not double-launch r00001.
        row_before = store.get_entity(
            names.TABLE_JOBSCHEDULES, pool.id, "race")
        assert schedules.run_due_schedules(
            store, pool, jobs, now=t0 + 1.5) == ["race-r00001"]
        stale = _StaleScheduleReadStore(store, row_before)
        assert schedules.run_due_schedules(
            stale, pool, jobs, now=t0 + 1.5) == []
        # Exactly one row per instance; run_number advanced once per
        # real launch (the lost updates never landed).
        final = store.get_entity(
            names.TABLE_JOBSCHEDULES, pool.id, "race")
        assert final["run_number"] == 2
        assert final["active_instance"] == "race-r00001"
        for inst in ("race-r00000", "race-r00001"):
            assert jobs_mgr.get_job(store, pool.id, inst)
    finally:
        substrate.stop_all()


def test_schedule_launches_instances():
    store, substrate, pool = make_env()
    try:
        jobs = settings_mod.job_settings_list({"job_specifications": [{
            "id": "sched",
            "recurrence": {"schedule": {
                "recurrence_interval_seconds": 1}},
            "tasks": [{"command": "echo tick"}],
        }]})
        t0 = time.time()
        launched = schedules.run_due_schedules(store, pool, jobs,
                                               now=t0)
        assert launched == ["sched-r00000"]
        # Immediately re-evaluating does nothing (interval not passed).
        assert schedules.run_due_schedules(store, pool, jobs,
                                           now=t0 + 0.2) == []
        assert schedules.run_due_schedules(
            store, pool, jobs, now=t0 + 1.5) == ["sched-r00001"]
        tasks = jobs_mgr.wait_for_tasks(store, "pool1", "sched-r00000",
                                        timeout=30)
        assert tasks[0]["state"] == "completed"
    finally:
        substrate.stop_all()


def test_schedule_run_exclusive_waits():
    store, substrate, pool = make_env()
    try:
        jobs = settings_mod.job_settings_list({"job_specifications": [{
            "id": "sx",
            "recurrence": {
                "schedule": {"recurrence_interval_seconds": 1},
                "job_manager": {"run_exclusive": True,
                                "monitor_task_completion": True}},
            "tasks": [{"command": "sleep 30"}],
        }]})
        t0 = time.time()
        assert schedules.run_due_schedules(store, pool, jobs, now=t0)
        # Interval elapsed but previous instance still active.
        assert schedules.run_due_schedules(
            store, pool, jobs, now=t0 + 2.0) == []
    finally:
        substrate.stop_all()


def test_schedule_daemon_loop():
    """The scheduler daemon launches instances over time and stops at
    max_recurrences."""
    store, substrate, pool = make_env()
    try:
        jobs = settings_mod.job_settings_list({"job_specifications": [{
            "id": "dsched",
            "recurrence": {"schedule": {
                "recurrence_interval_seconds": 1}},
            "tasks": [{"command": "echo tick"}],
        }]})
        launched = schedules.run_schedule_daemon(
            store, pool, jobs, poll_interval=0.2, max_recurrences=2)
        assert launched >= 2
        assert jobs_mgr.get_job(store, "pool1", "dsched-r00000")
        assert jobs_mgr.get_job(store, "pool1", "dsched-r00001")
    finally:
        substrate.stop_all()


def test_heimdall_daemon_loop(tmp_path):
    """heimdall.run_daemon refreshes file_sd until stopped."""
    import os
    import threading
    import time
    from batch_shipyard_tpu.monitor import heimdall
    store, substrate, pool = make_env()
    try:
        heimdall.add_pool_to_monitor(store, "pool1")
        stop = threading.Event()
        thread = threading.Thread(
            target=heimdall.run_daemon,
            args=(store, str(tmp_path / "sd")),
            kwargs={"poll_interval": 0.1, "stop_event": stop},
            daemon=True)
        thread.start()
        deadline = time.monotonic() + 10
        path = tmp_path / "sd" / "shipyard_targets.json"
        while time.monotonic() < deadline and not path.exists():
            time.sleep(0.05)
        stop.set()
        thread.join(timeout=5)
        assert path.exists()
        import json as json_mod
        assert json_mod.loads(path.read_text())
    finally:
        substrate.stop_all()


def test_auto_pool_job_lifecycle():
    """auto_pool: the job provisions its own pool, runs there, and the
    reaper deletes the pool once the job completes (reference
    _construct_auto_pool_specification, fleet.py:1768)."""
    from batch_shipyard_tpu import fleet
    from batch_shipyard_tpu.pool import manager as pool_mgr

    ctx = fleet.load_context(extra={
        "credentials": {"credentials": {
            "storage": {"backend": "memory"}}},
        "pool": {"pool_specification": {
            "id": "mainpool", "substrate": "fake",
            "tpu": {"accelerator_type": "v5litepod-4"},
            "max_wait_time_seconds": 30}},
        "jobs": {"job_specifications": [{
            "id": "apjob",
            "auto_pool": {"pool_lifetime": "job"},
            "tasks": [{"command": "echo auto-pool-ran"}]}]},
    })
    try:
        submitted = fleet.action_jobs_add(ctx)
        assert submitted == {"apjob": 1}
        # The job landed on its own derived pool, not the configured one.
        pools = {p["_rk"] for p in pool_mgr.list_pools(ctx.store)}
        assert "apjob-autopool" in pools
        assert "mainpool" not in pools
        tasks = jobs_mgr.wait_for_tasks(ctx.store, "apjob-autopool",
                                        "apjob", timeout=30)
        assert tasks[0]["state"] == "completed"
        # auto_complete was forced; once completed, the reaper removes
        # the pool.
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            job = jobs_mgr.get_job(ctx.store, "apjob-autopool", "apjob")
            if job.get("state") == "completed":
                break
            time.sleep(0.1)
        reaped = fleet.action_autopool_reap(ctx)
        assert reaped == ["apjob-autopool"]
        assert not pool_mgr.pool_exists(ctx.store, "apjob-autopool")
    finally:
        for sub in ctx._substrates.values():
            getattr(sub, "stop_all", lambda: None)()


def test_auto_scratch_lifecycle():
    """auto_scratch (BeeOND analog): tasks of the job share a per-job
    scratch dir via SHIPYARD_JOB_SCRATCH; the dir exists for the job's
    lifetime and is removed at job release."""
    import os

    conf = {"pool_specification": {
        "id": "scratchpool", "substrate": "fake",
        "tpu": {"accelerator_type": "v5litepod-4"},  # single node
        "max_wait_time_seconds": 30,
    }}
    store = MemoryStateStore()
    substrate = FakePodSubstrate(store)
    pool = settings_mod.pool_settings(conf)
    pool_mgr.create_pool(store, substrate, pool, GLOBAL, conf)
    try:
        jobs = settings_mod.job_settings_list({"job_specifications": [{
            "id": "scratchjob",
            "auto_scratch": True,
            "auto_complete": True,
            # Release harvests scratch BEFORE its lifetime ends.
            "job_release": {"command":
                            "sh -c 'cp $SHIPYARD_JOB_SCRATCH/marker "
                            "$SHIPYARD_JOB_SHARED_DIR/harvested'"},
            "tasks": [
                {"id": "writer",
                 "command": "sh -c 'echo payload-42 > "
                            "$SHIPYARD_JOB_SCRATCH/marker'"},
                {"id": "reader", "depends_on": ["writer"],
                 "command": "sh -c 'cat "
                            "$SHIPYARD_JOB_SCRATCH/marker'"},
            ]}]})
        jobs_mgr.add_jobs(store, pool, jobs)
        tasks = jobs_mgr.wait_for_tasks(store, "scratchpool",
                                        "scratchjob", timeout=60)
        assert all(t["state"] == "completed" for t in tasks), tasks
        out = jobs_mgr.get_task_output(store, "scratchpool",
                                       "scratchjob", "reader")
        assert out.strip() == b"payload-42"
        # Job release (auto_complete fan-out) removes the scratch dir.
        node_id = FakePodSubstrate.node_id("scratchpool", 0, 0)
        scratch = os.path.join(substrate.work_root, "scratchpool",
                               node_id, "scratch", "scratchjob")
        deadline = time.monotonic() + 30
        while os.path.isdir(scratch):
            assert time.monotonic() < deadline, \
                f"scratch dir {scratch} not cleaned up"
            time.sleep(0.25)
        job = store.get_entity(names.TABLE_JOBS, "scratchpool",
                               "scratchjob")
        assert job["state"] == "completed"
        harvested = os.path.join(substrate.work_root, "scratchpool",
                                 node_id, "shared", "scratchjob",
                                 "harvested")
        assert os.path.isfile(harvested)
        with open(harvested) as fh:
            assert fh.read().strip() == "payload-42"
    finally:
        substrate.stop_all()


def test_auto_scratch_preserved_when_harvest_fails():
    """If the job-release (harvest) command fails, the scratch dir
    must NOT be deleted — partially-harvested data would be
    irrecoverable (advisor r2 #3)."""
    import os

    conf = {"pool_specification": {
        "id": "scratchpool2", "substrate": "fake",
        "tpu": {"accelerator_type": "v5litepod-4"},
        "max_wait_time_seconds": 30,
    }}
    store = MemoryStateStore()
    substrate = FakePodSubstrate(store)
    pool = settings_mod.pool_settings(conf)
    pool_mgr.create_pool(store, substrate, pool, GLOBAL, conf)
    try:
        jobs = settings_mod.job_settings_list({"job_specifications": [{
            "id": "scratchjob2",
            "auto_scratch": True,
            "auto_complete": True,
            "job_release": {"command": "sh -c 'exit 3'"},
            "tasks": [
                {"id": "writer",
                 "command": "sh -c 'echo keep-me > "
                            "$SHIPYARD_JOB_SCRATCH/marker'"},
            ]}]})
        jobs_mgr.add_jobs(store, pool, jobs)
        tasks = jobs_mgr.wait_for_tasks(store, "scratchpool2",
                                        "scratchjob2", timeout=60)
        assert all(t["state"] == "completed" for t in tasks), tasks
        node_id = FakePodSubstrate.node_id("scratchpool2", 0, 0)
        scratch = os.path.join(substrate.work_root, "scratchpool2",
                               node_id, "scratch", "scratchjob2")
        # Wait for the job to complete (release ran and failed).
        deadline = time.monotonic() + 30
        while True:
            job = store.get_entity(names.TABLE_JOBS, "scratchpool2",
                                   "scratchjob2")
            if job["state"] == "completed":
                break
            assert time.monotonic() < deadline, job
            time.sleep(0.25)
        # Scratch survives the failed harvest.
        marker = os.path.join(scratch, "marker")
        assert os.path.isfile(marker), \
            f"scratch deleted despite failed harvest: {scratch}"
        with open(marker) as fh:
            assert fh.read().strip() == "keep-me"
    finally:
        substrate.stop_all()


def test_task_env_secret_resolved_on_node(monkeypatch):
    """environment_variables values may be secret:// refs (reference
    convoy/batch.py:4556-4577 keyvault env merge): the state store
    holds only the ref; the node agent resolves it at launch and the
    task sees the plaintext."""
    monkeypatch.setenv("TASK_API_KEY_TEST", "sk-live-abc123")
    conf = {"pool_specification": {
        "id": "secretpool", "substrate": "fake",
        "tpu": {"accelerator_type": "v5litepod-4"},
        "max_wait_time_seconds": 30,
    }}
    store = MemoryStateStore()
    substrate = FakePodSubstrate(store)
    pool = settings_mod.pool_settings(conf)
    pool_mgr.create_pool(store, substrate, pool, GLOBAL, conf)
    try:
        jobs = settings_mod.job_settings_list({"job_specifications": [{
            "id": "secretjob",
            "tasks": [{
                "id": "t0",
                "environment_variables": {
                    "API_KEY": "secret://env/TASK_API_KEY_TEST",
                    "PLAIN": "not-a-secret",
                },
                "command": "sh -c 'echo -n $API_KEY:$PLAIN'",
            }]}]})
        jobs_mgr.add_jobs(store, pool, jobs)
        tasks = jobs_mgr.wait_for_tasks(store, "secretpool",
                                        "secretjob", timeout=60)
        assert all(t["state"] == "completed" for t in tasks), tasks
        out = jobs_mgr.get_task_output(store, "secretpool",
                                       "secretjob", "t0")
        assert out == b"sk-live-abc123:not-a-secret"
        # The stored task spec still holds the ref, not the value.
        task = store.get_entity(names.TABLE_TASKS,
                                "secretpool$secretjob", "t0")
        spec_env = task["spec"]["environment_variables"]
        assert spec_env["API_KEY"] == "secret://env/TASK_API_KEY_TEST"
    finally:
        substrate.stop_all()


def test_pool_resident_schedule_service_fires_without_cli():
    """pool_services.schedules: the recurrence manager runs ON the
    pool (worker 0's agent) — registered schedules fire with no CLI
    daemon process alive (reference
    cargo/recurrent_job_manager.py:187)."""
    conf = {"pool_specification": {
        "id": "svcpool", "substrate": "fake",
        "tpu": {"accelerator_type": "v5litepod-4"},
        "max_wait_time_seconds": 30,
        "pool_services": {"schedules": True,
                          "poll_interval_seconds": 0.2},
    }}
    store = MemoryStateStore()
    substrate = FakePodSubstrate(store)
    pool = settings_mod.pool_settings(conf)
    pool_mgr.create_pool(store, substrate, pool, GLOBAL, conf)
    try:
        # Register a 1-second recurrence template; nothing else runs
        # client-side from here on.
        schedules.register_schedules(store, "svcpool", {
            "job_specifications": [{
                "id": "recurjob",
                "recurrence": {"schedule": {
                    "recurrence_interval_seconds": 1}},
                "tasks": [{"command": "true"}],
            }]})
        deadline = time.monotonic() + 30
        seen = set()
        while time.monotonic() < deadline and len(seen) < 2:
            for row in store.query_entities(names.TABLE_JOBS,
                                            partition_key="svcpool"):
                if row["_rk"].startswith("recurjob-r"):
                    seen.add(row["_rk"])
            time.sleep(0.2)
        assert len(seen) >= 2, (
            f"pool-resident scheduler fired {len(seen)} instances; "
            f"expected >=2 recurrences with no CLI process")
    finally:
        substrate.stop_all()


def test_pool_resident_autoscale_service_resizes():
    """pool_services.autoscale: the tick daemon runs on worker 0 with
    the substrate handle — a user formula demanding more slices grows
    the pool with no CLI process alive."""
    conf = {"pool_specification": {
        "id": "aspool", "substrate": "fake",
        "tpu": {"accelerator_type": "v5litepod-4"},
        "max_wait_time_seconds": 30,
        "autoscale": {"enabled": True, "formula": "2"},
        "pool_services": {"autoscale": True,
                          "poll_interval_seconds": 0.2},
    }}
    store = MemoryStateStore()
    substrate = FakePodSubstrate(store)
    pool = settings_mod.pool_settings(conf)
    pool_mgr.create_pool(store, substrate, pool, GLOBAL, conf)
    try:
        from batch_shipyard_tpu.pool import autoscale as as_mod
        as_mod.enable_autoscale(store, pool)
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            slices = {n.get("slice_index")
                      for n in store.query_entities(
                          names.TABLE_NODES, partition_key="aspool")}
            if len(slices) >= 2:
                break
            time.sleep(0.2)
        assert len(slices) >= 2, \
            f"autoscale service never grew the pool (slices={slices})"
    finally:
        substrate.stop_all()


def test_shared_auto_scratch_one_namespace_across_gang():
    """auto_scratch: shared — worker 0 hosts the scratch dir and the
    whole gang sees ONE POSIX namespace (the reference's BeeOND
    shared-parallel-fs pattern, shipyard_auto_scratch.sh): an instance
    on another node writes a file, and the reader on worker 0 sees it
    at the same SHIPYARD_JOB_SCRATCH path."""
    import os

    conf = {"pool_specification": {
        "id": "sharedscratch", "substrate": "fake",
        # 4 workers on one slice.
        "tpu": {"accelerator_type": "v5litepod-16"},
        "max_wait_time_seconds": 60,
    }}
    store = MemoryStateStore()
    substrate = FakePodSubstrate(store)
    pool = settings_mod.pool_settings(conf)
    pool_mgr.create_pool(store, substrate, pool, GLOBAL, conf)
    try:
        jobs = settings_mod.job_settings_list({"job_specifications": [{
            "id": "gangscratch",
            "auto_scratch": "shared",
            "auto_complete": True,
            "tasks": [
                # Every gang instance writes its own marker into the
                # SHARED namespace...
                {"id": "writers",
                 "command": "sh -c 'echo from-$SHIPYARD_NODE_INDEX > "
                            "$SHIPYARD_JOB_SCRATCH/"
                            "w$SHIPYARD_NODE_INDEX'",
                 "multi_instance": {"num_instances": 4}},
                # ...and a follow-up task (lands on one node) reads
                # them ALL back through the same path.
                {"id": "reader", "depends_on": ["writers"],
                 "command": "sh -c 'cat $SHIPYARD_JOB_SCRATCH/w0 "
                            "$SHIPYARD_JOB_SCRATCH/w1 "
                            "$SHIPYARD_JOB_SCRATCH/w2 "
                            "$SHIPYARD_JOB_SCRATCH/w3'"},
            ]}]})
        jobs_mgr.add_jobs(store, pool, jobs)
        tasks = jobs_mgr.wait_for_tasks(store, "sharedscratch",
                                        "gangscratch", timeout=90)
        assert all(t["state"] == "completed" for t in tasks), tasks
        out = jobs_mgr.get_task_output(store, "sharedscratch",
                                       "gangscratch", "reader")
        assert out.split() == [b"from-0", b"from-1", b"from-2",
                               b"from-3"], out
        # Lifetime: the host's dir goes away at job release and the
        # published host record is cleaned up.
        node0 = FakePodSubstrate.node_id("sharedscratch", 0, 0)
        scratch = os.path.join(substrate.work_root, "sharedscratch",
                               node0, "scratch", "gangscratch")
        deadline = time.monotonic() + 30
        while os.path.isdir(scratch):
            assert time.monotonic() < deadline, scratch
            time.sleep(0.25)
        from batch_shipyard_tpu.state.base import NotFoundError
        try:
            store.get_entity(names.TABLE_JOBPREP,
                             "sharedscratch$gangscratch",
                             "#scratchhost")
            raise AssertionError("scratchhost record not cleaned up")
        except NotFoundError:
            pass
    finally:
        substrate.stop_all()


def test_job_priority_overtakes_backlog():
    """A high-priority job submitted behind a large sweep backlog
    completes before the backlog drains (Azure Batch job-priority
    semantics the reference inherits; jobs.yaml priority)."""
    conf = {"pool_specification": {
        "id": "prio", "substrate": "fake",
        "tpu": {"accelerator_type": "v5litepod-4"},  # 1 node
        "task_slots_per_node": 1,
        "max_wait_time_seconds": 30,
    }}
    store = MemoryStateStore()
    substrate = FakePodSubstrate(store)
    pool = settings_mod.pool_settings(conf)
    pool_mgr.create_pool(store, substrate, pool, GLOBAL, conf)
    try:
        sweep = settings_mod.job_settings_list({"job_specifications": [{
            "id": "sweep",
            "tasks": [{"command": "echo sweep",
                       "task_factory": {"repeat": 200}}],
        }]})
        jobs_mgr.add_jobs(store, pool, sweep)
        urgent = settings_mod.job_settings_list({"job_specifications": [{
            "id": "urgent", "priority": 100,
            "tasks": [{"command": "echo urgent"}],
        }]})
        jobs_mgr.add_jobs(store, pool, urgent)
        # The urgent task rides the hi band...
        assert store.queue_length(
            names.task_queue("prio", 0, "hi")) == 1
        tasks = jobs_mgr.wait_for_tasks(store, "prio", "urgent",
                                        timeout=30)
        assert tasks[0]["state"] == "completed"
        # ... and finished while the sweep backlog was still deep.
        sweep_pending = sum(
            1 for t in jobs_mgr.list_tasks(store, "prio", "sweep")
            if t.get("state") == "pending")
        assert sweep_pending > 50, (
            f"urgent overtook only {200 - sweep_pending} sweep tasks")
    finally:
        substrate.stop_all()


def test_merge_tasks_into_job_collision_fixup():
    """Direct merge API: generic ids renumber past the existing max;
    explicit colliding ids are rejected."""
    store, substrate, pool = make_env("mpool")
    try:
        jobs = settings_mod.job_settings_list({"job_specifications": [{
            "id": "jm2", "tasks": [{"command": "echo first"}]}]})
        jobs_mgr.add_jobs(store, pool, jobs)
        jobs_mgr.wait_for_tasks(store, "mpool", "jm2", timeout=30)
        added = jobs_mgr.merge_tasks_into_job(
            store, pool, jobs[0], "mpool")
        assert added == 1
        tasks = jobs_mgr.wait_for_tasks(store, "mpool", "jm2",
                                        timeout=30)
        assert sorted(t["_rk"] for t in tasks) == [
            "task-00000", "task-00001"]
        # Explicit id collision -> error
        named = settings_mod.job_settings_list({"job_specifications": [{
            "id": "jm2",
            "tasks": [{"id": "fixed-id", "command": "echo x"}]}]})
        jobs_mgr.merge_tasks_into_job(store, pool, named[0], "mpool")
        with pytest.raises(jobs_mgr.JobExistsError):
            jobs_mgr.merge_tasks_into_job(store, pool, named[0],
                                          "mpool")
    finally:
        substrate.stop_all()


def test_migrate_preserves_priority_band():
    """A migrated high-priority job's pending tasks land on the
    DESTINATION pool's hi band (not the normal band, where they would
    queue behind sweeps)."""
    store = MemoryStateStore()
    substrate = FakePodSubstrate(store)
    confs = {}
    for pid in ("mig-src", "mig-dst"):
        conf = {"pool_specification": {
            "id": pid, "substrate": "fake",
            "tpu": {"accelerator_type": "v5litepod-4"},
            "max_wait_time_seconds": 30}}
        confs[pid] = settings_mod.pool_settings(conf)
        pool_mgr.create_pool(store, substrate, confs[pid], GLOBAL,
                             conf)
    # Quiesce agents so tasks stay pending for the migration.
    substrate.stop_all()
    jobs = settings_mod.job_settings_list({"job_specifications": [{
        "id": "mjob", "priority": 50,
        "tasks": [{"command": "echo hi-pri"}]}]})
    jobs_mgr.add_jobs(store, confs["mig-src"], jobs)
    assert store.queue_length(
        names.task_queue("mig-src", 0, "hi")) == 1
    jobs_mgr.disable_job(store, "mig-src", "mjob")
    moved = jobs_mgr.migrate_job(store, "mig-src", "mjob", "mig-dst")
    assert moved == 1
    assert store.queue_length(
        names.task_queue("mig-dst", 0, "hi")) == 1
    assert store.queue_length(names.task_queue("mig-dst", 0)) == 0


def test_job_env_block_from_secret(monkeypatch):
    """environment_variables_keyvault_secret_id: a secret holding a
    WHOLE env map (JSON) resolves on node and merges into task env,
    with explicit per-key env winning (reference keyvault.py:176 —
    env blocks ride the vault, never the state store)."""
    monkeypatch.setenv(
        "JOB_ENV_BLOCK",
        json.dumps({"FROM_BLOCK": "vault-value", "SHARED": "block"}))
    store, substrate, pool = make_env("envsecret")
    try:
        jobs = settings_mod.job_settings_list({"job_specifications": [{
            "id": "ej",
            "environment_variables": {"SHARED": "explicit"},
            "environment_variables_keyvault_secret_id":
                "secret://env/JOB_ENV_BLOCK",
            "tasks": [{"id": "t",
                       "command": "sh -c 'echo $FROM_BLOCK:$SHARED'"}],
        }]})
        jobs_mgr.add_jobs(store, pool, jobs)
        tasks = jobs_mgr.wait_for_tasks(store, "envsecret", "ej",
                                        timeout=30)
        assert tasks[0]["state"] == "completed"
        out = jobs_mgr.get_task_output(store, "envsecret", "ej", "t")
        assert out.strip() == b"vault-value:explicit"
        # The state store never saw the plaintext — only the ref.
        spec = tasks[0]["spec"]
        assert spec["environment_variables_secret_id"] == \
            "secret://env/JOB_ENV_BLOCK"
        assert "vault-value" not in json.dumps(spec)
    finally:
        substrate.stop_all()


def test_env_block_dotenv_lines(monkeypatch, tmp_path):
    """The env-block secret also accepts KEY=VALUE lines."""
    from batch_shipyard_tpu.agent.node_agent import (
        NodeAgent, NodeIdentity)
    monkeypatch.setenv("DOTENV_BLOCK",
                       "# comment\nA=1\nB = two \n\nbad-line\n")
    conf = {"pool_specification": {
        "id": "x", "substrate": "fake",
        "tpu": {"accelerator_type": "v5litepod-4"}}}
    agent = NodeAgent(
        MemoryStateStore(),
        NodeIdentity(pool_id="x", node_id="n", node_index=0,
                     hostname="h", internal_ip="ip"),
        settings_mod.pool_settings(conf),
        work_dir=str(tmp_path))
    block = agent._resolve_env_block("j", "secret://env/DOTENV_BLOCK")
    assert block == {"A": "1", "B": "two"}


def test_env_block_secret_failure_fails_task_cleanly():
    """An unresolvable env-block secret FAILS the task with the
    reason instead of bouncing its queue message forever."""
    store, substrate, pool = make_env("envfail")
    try:
        jobs = settings_mod.job_settings_list({"job_specifications": [{
            "id": "fj",
            "environment_variables_keyvault_secret_id":
                "secret://env/DOES_NOT_EXIST_ANYWHERE",
            "tasks": [{"id": "t", "command": "echo never"}],
        }]})
        jobs_mgr.add_jobs(store, pool, jobs)
        tasks = jobs_mgr.wait_for_tasks(store, "envfail", "fj",
                                        timeout=30)
        assert tasks[0]["state"] == "failed"
        assert "environment synthesis failed" in tasks[0]["error"]
    finally:
        substrate.stop_all()

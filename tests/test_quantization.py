"""Int8 quantization kernel tests (interpret mode): round-trip error
bounds, unbiasedness of stochastic rounding, matmul accuracy, QAT
gradients."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.experimental.pallas import tpu as pltpu

from batch_shipyard_tpu.ops import quantization as q


@pytest.fixture(autouse=True)
def interpret_mode():
    with pltpu.force_tpu_interpret_mode():
        yield


def test_quantize_roundtrip_error_bound():
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randn(64, 128), jnp.float32)
    values, scales = q.quantize_int8(x, seed=1)
    assert values.dtype == jnp.int8
    recon = q.dequantize_int8(values, scales)
    # Error bounded by one quantization step per element.
    step = np.asarray(scales)
    err = np.abs(np.asarray(recon) - np.asarray(x))
    assert (err <= step + 1e-6).all()


def test_stochastic_rounding_unbiased():
    # One element pins the scale at 1.0/127 per step; the rest sit at
    # a non-integer multiple of the step (25.4 steps), so rounding IS
    # stochastic — the mean over seeds must approach the true value
    # (a nearest-rounding implementation would be off by a fixed
    # ~0.4 steps).
    x = jnp.full((8, 128), 0.2, jnp.float32).at[:, 0].set(1.0)
    step = 1.0 / 127.0
    totals = []
    for seed in range(30):
        values, scales = q.quantize_int8(x, seed=seed)
        recon = q.dequantize_int8(values, scales)
        totals.append(float(jnp.mean(recon[:, 1:])))
    assert abs(np.mean(totals) - 0.2) < 0.15 * step
    # And individual draws really do differ (stochastic, not nearest).
    assert np.std(totals) > 0


def test_blocking_handles_non_divisible_dims():
    # 300 rows with preferred block 256 -> divisor blocks, never a
    # whole-array fallback.
    x = jnp.asarray(np.random.RandomState(3).randn(300, 128),
                    jnp.float32)
    values, scales = q.quantize_int8(x, seed=0)
    assert values.shape == (300, 128)
    recon = q.dequantize_int8(values, scales)
    assert (np.abs(np.asarray(recon - x)) <=
            np.asarray(scales) + 1e-6).all()


def test_int8_matmul_accuracy():
    rng = np.random.RandomState(1)
    x = jnp.asarray(rng.randn(32, 64), jnp.float32)
    w = jnp.asarray(rng.randn(64, 48), jnp.float32)
    exact = np.asarray(x) @ np.asarray(w)
    got = np.asarray(q.quantized_linear(x, w, 3))
    # int8 x int8 with stochastic rounding: ~3% mean relative error
    # for gaussian operands at K=64 (stochastic rounding trades bias
    # for ~2x the variance of nearest rounding).
    denom = np.maximum(np.abs(exact), 1.0)
    assert (np.abs(got - exact) / denom).mean() < 0.05


def test_quantized_linear_gradients_full_precision():
    rng = np.random.RandomState(2)
    x = jnp.asarray(rng.randn(16, 32), jnp.float32)
    w = jnp.asarray(rng.randn(32, 24), jnp.float32)

    def loss_q(x, w):
        return jnp.sum(q.quantized_linear(x, w, 0) ** 2)

    gx, gw = jax.grad(loss_q, argnums=(0, 1))(x, w)
    # Straight-through backward: compare against the dense-matmul
    # gradient of the QUANTIZED forward output: d/dx sum(y^2) = 2 y w^T
    y = q.quantized_linear(x, w, 0)
    np.testing.assert_allclose(np.asarray(gx),
                               np.asarray(2 * y @ w.T), rtol=1e-5)
    np.testing.assert_allclose(np.asarray(gw),
                               np.asarray(2 * x.T @ y), rtol=1e-5)


def test_quant_dense_matches_dense():
    """QuantDense (the quantize_matmuls=True model path) approximates
    nn.Dense with the same kernel and differentiates through the QAT
    straight-through backward."""
    from flax import linen as nn

    from batch_shipyard_tpu.models.transformer import QuantDense

    rng = np.random.RandomState(4)
    x = jnp.asarray(rng.randn(2, 16, 32), jnp.float32)
    qd = QuantDense(24, dtype=jnp.float32, param_dtype=jnp.float32)
    params = qd.init(jax.random.PRNGKey(0), x)["params"]
    got = qd.apply({"params": params}, x)
    assert got.shape == (2, 16, 24)
    exact = x @ params["kernel"]
    denom = np.maximum(np.abs(np.asarray(exact)), 1.0)
    assert (np.abs(np.asarray(got - exact)) / denom).mean() < 0.05
    grads = jax.grad(
        lambda p: jnp.sum(qd.apply({"params": p}, x) ** 2))(params)
    assert jnp.isfinite(grads["kernel"]).all()
    assert float(jnp.abs(grads["kernel"]).sum()) > 0


def test_quantized_transformer_config_trains():
    """A tiny quantize_matmuls=True TransformerLM takes a finite
    training-loss gradient step (interpret mode)."""
    from batch_shipyard_tpu.models import transformer as tfm

    cfg = tfm.TransformerConfig(
        vocab_size=64, d_model=32, n_layers=1, n_heads=2, d_head=16,
        d_ff=64, max_seq_len=16, dtype=jnp.float32,
        param_dtype=jnp.float32, quantize_matmuls=True,
        attention_fn=lambda q_, k_, v_, causal: tfm.attn_ops.attention(
            q_, k_, v_, causal=causal, impl="blockwise", block_size=16))
    model = tfm.TransformerLM(cfg)
    rng = np.random.RandomState(5)
    tokens = jnp.asarray(rng.randint(0, 64, (2, 16)), jnp.int32)
    params = model.init(jax.random.PRNGKey(0), tokens)["params"]

    def loss_fn(params):
        logits = model.apply({"params": params}, tokens)
        return tfm.lm_loss(logits, tokens)

    loss, grads = jax.value_and_grad(loss_fn)(params)
    assert np.isfinite(float(loss))
    leaves = jax.tree_util.tree_leaves(grads)
    assert all(jnp.isfinite(g).all() for g in leaves)

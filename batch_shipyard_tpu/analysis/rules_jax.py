"""JAX/determinism rules — enforced WITHOUT importing JAX.

Pure AST scans for the three accelerator bug classes this repo has
paid for: donated-buffer reuse (a runtime XLA error at best, silent
garbage at worst — the PR 6 opt-state sharding fix was adjacent to
exactly this), restoring over an undrained async checkpoint writer
(the PR 10 preemption drain contract), and wall-clock/global-random
calls inside functions whose whole value is determinism (chaos plans
named by seed+fingerprint, compile-cache identity keys that must
match across every node of a pool).
"""

from __future__ import annotations

import ast
from typing import Optional

from batch_shipyard_tpu.analysis.core import (
    AnalysisContext, Finding, call_name, keyword_arg, rule)

# Pure-by-contract functions: (file, function-name) pairs whose
# docstrings promise determinism — chaos plans are "a pure function
# of (seed, shape)" (chaos/plan.py) and cache identity "pure over
# explicit args" (compilecache/manager.py). Registering a function
# here is how a module opts its contract into machine enforcement.
PURE_CONTRACTS = {
    "batch_shipyard_tpu/chaos/plan.py":
        {"generate", "fingerprint", "to_dict", "from_dict", "param"},
    "batch_shipyard_tpu/compilecache/manager.py":
        {"_stable", "config_digest", "identity_key"},
}

# Calls that break determinism / purity. random.Random(seed) is fine
# (and is the chaos plan's whole mechanism); the MODULE-level
# random.random()/uniform()/... draws from hidden global state.
_IMPURE_TIME = {"time", "monotonic", "perf_counter", "time_ns"}
_IMPURE_RANDOM = {"random", "uniform", "randint", "randrange",
                  "choice", "shuffle", "sample", "getrandbits"}


def _impure_call(node: ast.Call) -> Optional[str]:
    func = node.func
    if not isinstance(func, ast.Attribute):
        return None
    base = func.value
    if not isinstance(base, ast.Name):
        return None
    if base.id == "time" and func.attr in _IMPURE_TIME:
        return f"time.{func.attr}"
    if base.id == "random" and func.attr in _IMPURE_RANDOM:
        return f"random.{func.attr}"
    if base.id == "datetime" and func.attr in ("now", "utcnow",
                                               "today"):
        return f"datetime.{func.attr}"
    if base.id == "uuid" and func.attr.startswith("uuid"):
        return f"uuid.{func.attr}"
    if base.id == "os" and func.attr == "urandom":
        return "os.urandom"
    if base.id == "secrets":
        return f"secrets.{func.attr}"
    return None


@rule("jax-impure-pure-fn", family="jax")
def check_impure_pure_fn(ctx: AnalysisContext) -> list[Finding]:
    """A wall-clock, global-random, or uuid call inside a registered
    pure-by-contract function (PURE_CONTRACTS): chaos plans must
    replay identically from a seed (operators name scenarios by
    fingerprint) and compile-cache identity keys must digest
    identically on every node (a drifting key re-compiles the whole
    pool and silently disables seeding).

    Provenance: the PR 4 cache-key review, where an
    address-carrying config field made two identical nodes disagree
    on identity until config_digest learned to scrub it — clock or
    RNG input is the same bug with worse odds."""
    findings = []
    for src in ctx.python_files:
        contract = PURE_CONTRACTS.get(src.rel)
        if not contract:
            continue
        for fn in [n for n in ast.walk(src.tree)
                   if isinstance(n, ast.FunctionDef)
                   and n.name in contract]:
            for node in ast.walk(fn):
                if not isinstance(node, ast.Call):
                    continue
                impure = _impure_call(node)
                if impure:
                    findings.append(Finding(
                        rule="jax-impure-pure-fn", path=src.rel,
                        line=node.lineno,
                        message=(f"{impure}() inside pure-by-"
                                 f"contract function {fn.name!r}; "
                                 f"determinism is this function's "
                                 f"contract — thread the value in "
                                 f"as an argument")))
    return findings


def _donated_positions(node: ast.Call) -> Optional[set[int]]:
    """Donated arg positions of a jax.jit(...) call expression, or
    None when it doesn't donate."""
    donate = keyword_arg(node, "donate_argnums")
    if donate is None:
        return None
    if isinstance(donate, ast.Constant) and \
            isinstance(donate.value, int):
        return {donate.value}
    if isinstance(donate, (ast.Tuple, ast.List)):
        out = set()
        for elt in donate.elts:
            if isinstance(elt, ast.Constant) and \
                    isinstance(elt.value, int):
                out.add(elt.value)
        return out
    return set()


def _collect_donating_jits(tree: ast.AST) -> dict[str, set[int]]:
    """name -> donated positions, for both idioms:
    step = jax.jit(fn, donate_argnums=(0,)) assignments and
    @partial(jax.jit, donate_argnums=(0,)) decorators."""
    donating: dict[str, set[int]] = {}

    def jit_call(call: ast.Call) -> Optional[ast.Call]:
        name = call_name(call)
        if name == "jit":
            return call
        if name == "partial" and call.args:
            inner = call.args[0]
            if isinstance(inner, (ast.Attribute, ast.Name)) and \
                    (getattr(inner, "attr", None) == "jit"
                     or getattr(inner, "id", None) == "jit"):
                return call
        return None

    for node in ast.walk(tree):
        if isinstance(node, ast.Assign) and \
                isinstance(node.value, ast.Call):
            call = jit_call(node.value)
            if call is not None:
                positions = _donated_positions(call)
                if positions:
                    for target in node.targets:
                        if isinstance(target, ast.Name):
                            donating[target.id] = positions
        elif isinstance(node, ast.FunctionDef):
            for dec in node.decorator_list:
                if isinstance(dec, ast.Call):
                    call = jit_call(dec)
                    if call is not None:
                        positions = _donated_positions(call)
                        if positions:
                            donating[node.name] = positions
    return donating


def _own_statements(fn: ast.FunctionDef) -> list[ast.stmt]:
    """The function's statements in execution order, WITHOUT
    descending into nested function/class definitions (their bodies
    are separate scopes and separate simulations)."""
    out: list[ast.stmt] = []

    def visit(body: list[ast.stmt]) -> None:
        for stmt in body:
            if isinstance(stmt, (ast.FunctionDef,
                                 ast.AsyncFunctionDef,
                                 ast.ClassDef)):
                continue
            out.append(stmt)
            for field in ("body", "orelse", "finalbody"):
                child = getattr(stmt, field, None)
                if child:
                    visit(child)
            for handler in getattr(stmt, "handlers", []) or []:
                visit(handler.body)
    visit(fn.body)
    return out


@rule("jax-donated-reuse", family="jax")
def check_donated_reuse(ctx: AnalysisContext) -> list[Finding]:
    """A variable passed at a donated position of a jit'd function is
    read again in a LATER statement before being rebound: donation
    hands the buffer to XLA, so the old reference is garbage — a
    runtime error when you're lucky, silently corrupt numerics when
    you're not.

    Provenance: the PR 6 train-step review (donated opt-state
    aliased to a differently-sharded output was a runtime XLA error
    under tp); the blessed shape rebinds in one statement:
    ``params, opt = step(params, opt, batch)``."""
    findings = []
    for src in ctx.python_files:
        donating = _collect_donating_jits(src.tree)
        if not donating:
            continue
        for fn in [n for n in ast.walk(src.tree)
                   if isinstance(n, ast.FunctionDef)]:
            # donated name -> line it was consumed at. Statement
            # granularity: a statement's own loads are checked
            # against PRIOR donations only (the donating call's own
            # arguments are legitimate last uses), then its donations
            # register, then its stores rebind.
            consumed: dict[str, int] = {}
            for stmt in _own_statements(fn):
                donates: list[tuple[str, int]] = []
                loads: list[tuple[str, int]] = []
                stores: list[str] = []
                for node in ast.walk(stmt):
                    if isinstance(node, ast.Call):
                        fname = (node.func.id
                                 if isinstance(node.func, ast.Name)
                                 else None)
                        if fname in donating:
                            for pos in donating[fname]:
                                if pos < len(node.args) and \
                                        isinstance(node.args[pos],
                                                   ast.Name):
                                    donates.append(
                                        (node.args[pos].id,
                                         node.lineno))
                    elif isinstance(node, ast.Name):
                        if isinstance(node.ctx, ast.Load):
                            loads.append((node.id, node.lineno))
                        else:
                            stores.append(node.id)
                for name, line in loads:
                    if name in consumed:
                        findings.append(Finding(
                            rule="jax-donated-reuse", path=src.rel,
                            line=line,
                            message=(f"{name!r} was donated to a "
                                     f"jit'd call on line "
                                     f"{consumed[name]} and is read "
                                     f"again before being rebound; "
                                     f"the buffer no longer "
                                     f"exists")))
                        del consumed[name]
                for name, line in donates:
                    consumed.setdefault(name, line)
                for name in stores:
                    consumed.pop(name, None)
    return findings


@rule("jax-restore-no-drain", family="jax")
def check_restore_no_drain(ctx: AnalysisContext) -> list[Finding]:
    """A blocking ``restore(...)`` call in a module that uses
    AsyncCheckpointManager, with no ``wait_until_finished`` earlier
    in the function and no manager-presence guard around it: an
    in-flight async persist can still be writing the very directory
    the restore reads — torn reads of a checkpoint that was COMMITTED
    from the writer's point of view a moment later.

    Provenance: the PR 10 preempt drain contract (async writer
    drained BEFORE exit/restore); AsyncCheckpointManager.restore
    drains internally, which is the blessed shape."""
    findings = []
    for src in ctx.python_files:
        uses_async = any(
            (isinstance(node, (ast.Name, ast.Attribute)) and
             (getattr(node, "id", None) == "AsyncCheckpointManager"
              or getattr(node, "attr", None)
              == "AsyncCheckpointManager"))
            or (isinstance(node, ast.alias) and
                node.name == "AsyncCheckpointManager")
            for node in ast.walk(src.tree))
        if not uses_async:
            continue
        for fn in [n for n in ast.walk(src.tree)
                   if isinstance(n, ast.FunctionDef)]:
            # Only functions with an async manager in scope are at
            # risk: a legacy params-only loader that never touches a
            # manager has no writer to drain.
            if "manager" not in ast.dump(fn).lower():
                continue
            drained_lines = [
                node.lineno for node in ast.walk(fn)
                if isinstance(node, ast.Call)
                and call_name(node) == "wait_until_finished"]
            # Map call -> enclosing If tests (a `self.manager is
            # None`-style guard legitimizes the blocking branch).
            def guarded(call: ast.Call) -> bool:
                for node in ast.walk(fn):
                    if isinstance(node, ast.If) and \
                            "manager" in ast.dump(node.test):
                        span = (node.lineno,
                                getattr(node, "end_lineno",
                                        node.lineno))
                        if span[0] <= call.lineno <= span[1]:
                            return True
                return False

            for node in ast.walk(fn):
                if not (isinstance(node, ast.Call)
                        and call_name(node) == "restore"):
                    continue
                # manager.restore drains internally — only the
                # module-level blocking loader is at risk.
                if isinstance(node.func, ast.Attribute) and \
                        isinstance(node.func.value, ast.Attribute) \
                        and node.func.value.attr == "manager":
                    continue
                if any(line < node.lineno for line in drained_lines):
                    continue
                if guarded(node):
                    continue
                findings.append(Finding(
                    rule="jax-restore-no-drain", path=src.rel,
                    line=node.lineno,
                    message=("blocking restore() in an async-"
                             "checkpoint module without draining "
                             "the writer first; call "
                             "wait_until_finished() or guard on "
                             "the manager's absence")))
    return findings


@rule("jax-blocking-save-in-train", family="jax")
def check_blocking_save_in_train(ctx: AnalysisContext,
                                 ) -> list[Finding]:
    """A direct blocking ``checkpoint.save()`` in a train workload
    reintroduces the full-persist step stall the zero-stall pipeline
    (PR 3) exists to remove, and skips the stale-step guard — drive
    checkpoints through checkpoint.TrainCheckpointer.

    Provenance: the duplicate-final-save bug in train_transformer
    (PR 3), migrated from test_names_consistency."""
    findings = []
    for src in ctx.python_files:
        if not (src.rel.startswith("batch_shipyard_tpu/workloads/"
                                   "train_")
                and src.rel.endswith(".py")):
            continue
        for node in ast.walk(src.tree):
            if isinstance(node, ast.Call) and \
                    isinstance(node.func, ast.Attribute) and \
                    node.func.attr == "save" and \
                    isinstance(node.func.value, ast.Name) and \
                    node.func.value.id == "checkpoint":
                findings.append(Finding(
                    rule="jax-blocking-save-in-train", path=src.rel,
                    line=node.lineno,
                    message=("direct blocking checkpoint.save() in "
                             "a train workload; use "
                             "checkpoint.TrainCheckpointer")))
    return findings

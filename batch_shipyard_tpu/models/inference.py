"""Autoregressive inference: KV-cache decode + sampling.

The framework's serving-side counterpart to the training path
(ROADMAP item; the reference had no inference story at all). Design:

  - prefill: ONE jitted full-sequence forward over the prompt writing
    all KV-cache rows in a single MXU-batched pass (the multi-token
    insert path of transformer._decode_attend) — prefill cost is one
    forward, not T_prompt sequential micro-steps;
  - decode: one token per step through the transformer's decode mode
    (flax 'cache' collection holding per-layer K/V + write index),
    inside a single jitted lax.scan — no per-token Python dispatch;
  - sampling: greedy, temperature, and top-k, driven by a jax PRNG key.

Works on CPU/TPU and under dp sharding (batch dim); cache lives on
device for the whole generation.
"""

from __future__ import annotations

import dataclasses
import functools

import jax
import jax.numpy as jnp

from batch_shipyard_tpu.models import transformer as tfm


@dataclasses.dataclass(frozen=True)
class SamplingConfig:
    temperature: float = 0.0   # 0 => greedy
    top_k: int = 0             # 0 => full distribution


def decode_config(config: tfm.TransformerConfig,
                  max_decode_len: int) -> tfm.TransformerConfig:
    return dataclasses.replace(
        config, decode=True, max_decode_len=max_decode_len,
        attention_fn=None, remat=False)


def init_cache(model: tfm.TransformerLM, params, batch_size: int):
    """Materialize an empty KV cache pytree for the decode model.

    model.init runs a forward pass, which WRITES the dummy token into
    slot 0 and bumps the index — zero everything so the cache starts
    truly empty."""
    variables = model.init(
        jax.random.PRNGKey(0),
        jnp.zeros((batch_size, 1), jnp.int32),
        positions=jnp.zeros((1,), jnp.int32))
    return jax.tree_util.tree_map(jnp.zeros_like, variables["cache"])


def _sample(logits, key, sampling: SamplingConfig):
    """logits: [B, vocab] fp32 -> token ids [B]."""
    if sampling.temperature <= 0.0:
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)
    logits = logits / sampling.temperature
    if sampling.top_k > 0:
        top_vals, _ = jax.lax.top_k(logits, sampling.top_k)
        cutoff = top_vals[:, -1][:, None]
        logits = jnp.where(logits < cutoff, -1e30, logits)
    return jax.random.categorical(key, logits, axis=-1).astype(
        jnp.int32)


@functools.partial(jax.jit, static_argnames=(
    "model", "num_tokens", "sampling"))
def generate(model: tfm.TransformerLM, params, cache, prompt,
             num_tokens: int, key,
             sampling: SamplingConfig = SamplingConfig()):
    """Generate num_tokens continuations of prompt [B, T_prompt].

    Returns (tokens [B, T_prompt + num_tokens], cache). The whole
    prefill + decode runs inside one jit; per-token work is a lax.scan
    step feeding the KV cache.
    """
    batch, prompt_len = prompt.shape

    def step(carry, _):
        cache, token, pos, key = carry
        logits, mutated = model.apply(
            {"params": params, "cache": cache}, token,
            positions=pos[None], mutable=["cache"])
        key, sample_key = jax.random.split(key)
        next_token = _sample(logits[:, 0].astype(jnp.float32),
                             sample_key, sampling)
        return ((mutated["cache"], next_token[:, None], pos + 1, key),
                next_token)

    # Prefill: ONE full-sequence forward through the multi-token
    # cache-insert path (transformer._decode_attend seq > 1) — all
    # prompt K/V land in the cache in a single MXU-batched pass
    # instead of a T_prompt-step scan. Only the last position's
    # logits are needed, so return_hidden + a [B, d] x [d, vocab]
    # matmul avoids materializing [B, T, vocab] fp32 logits.
    hidden, mutated = model.apply(
        {"params": params, "cache": cache}, prompt,
        return_hidden=True, mutable=["cache"])
    cache = mutated["cache"]
    pos = jnp.int32(prompt_len)
    embedding = params["embed"]["embedding"]
    last_logits = jnp.dot(hidden[:, -1].astype(jnp.float32),
                          embedding.astype(jnp.float32).T)
    key, sample_key = jax.random.split(key)
    first = _sample(last_logits, sample_key, sampling)
    (cache, _tok, _pos, _key), generated = jax.lax.scan(
        step, (cache, first[:, None], pos, key), None,
        length=num_tokens - 1)
    tokens = jnp.concatenate(
        [prompt, first[:, None],
         jnp.moveaxis(generated, 0, 1)], axis=1)
    return tokens, cache


def _rewind_cache(cache, steps):
    """Roll every layer's write index back by ``steps`` (scalar or
    [B]). Entries beyond the index are masked by _decode_attend and
    overwritten by the next insert, so the index IS the cache state —
    rewinding it un-commits speculated tokens in O(1). The paged
    cache's per-slot write cursor is its "length" leaf; rewinding it
    un-commits the same way (pages stay allocated, the next insert
    overwrites)."""
    def fix(path, leaf):
        if path and getattr(path[-1], "key", None) in ("index",
                                                       "length"):
            return leaf - steps
        return leaf
    return jax.tree_util.tree_map_with_path(fix, cache)


@functools.partial(jax.jit, static_argnames=(
    "target_model", "draft_model", "num_tokens", "gamma"))
def speculative_generate(target_model: tfm.TransformerLM,
                         target_params,
                         draft_model: tfm.TransformerLM,
                         draft_params,
                         prompt, num_tokens: int, gamma: int = 4):
    """Speculative decoding (Leviathan et al.): a cheap DRAFT model
    proposes ``gamma`` tokens autoregressively; the TARGET model
    scores the whole block in ONE MXU-batched forward through the
    multi-token cache-insert path and commits the longest validated
    prefix plus one target token. Greedy acceptance: outputs are
    BIT-IDENTICAL to target-only greedy decoding (the equivalence the
    tests pin), while the target runs a forward every ~(accepted+1)
    tokens instead of every token — the serving latency lever when
    the target is much larger than the draft.

    Batched: acceptance is synchronized to the batch MINIMUM each
    round. That is still exact per slot — a slot that could have
    accepted more receives the same tokens via the target's
    correction logits — it only costs throughput, never correctness
    (and keeps every shape static for jit).

    prompt: [B, P] int32 (P >= 1). Returns (tokens [B, P+num_tokens],
    stats dict: rounds, proposed, accepted — acceptance rate =
    accepted / proposed).

    Cache bookkeeping invariant: each model's cache holds every
    committed token EXCEPT the newest (``y``); each round feeds
    [y, d_1..d_gamma], so both caches advance gamma+1 and rewind by
    gamma - accepted (see _rewind_cache).
    """
    batch, prompt_len = prompt.shape
    cap = num_tokens + gamma + 1

    t_cache = init_cache(target_model, target_params, batch)
    d_cache = init_cache(draft_model, draft_params, batch)
    if prompt_len > 1:
        # Prefill both caches with prompt[:-1]; the last prompt token
        # is the first pending y.
        _, mut = target_model.apply(
            {"params": target_params, "cache": t_cache},
            prompt[:, :-1], return_hidden=True, mutable=["cache"])
        t_cache = mut["cache"]
        _, mut = draft_model.apply(
            {"params": draft_params, "cache": d_cache},
            prompt[:, :-1], return_hidden=True, mutable=["cache"])
        d_cache = mut["cache"]
    y0 = prompt[:, -1]

    t_embed = target_params["embed"]["embedding"]
    d_embed = draft_params["embed"]["embedding"]

    def draft_step(carry, _):
        cache, token, pos = carry
        hidden, mut = draft_model.apply(
            {"params": draft_params, "cache": cache}, token[:, None],
            return_hidden=True, positions=pos[None],
            mutable=["cache"])
        logits = jnp.dot(hidden[:, 0].astype(jnp.float32),
                         d_embed.astype(jnp.float32).T)
        nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
        return (mut["cache"], nxt, pos + 1), nxt

    def round_body(state):
        t_cache, d_cache, out, n_done, y, rounds, proposed, accepted \
            = state
        pos_y = prompt_len + n_done - 1
        # Draft proposes d_1..d_gamma (the final extra step only
        # inserts d_gamma's K/V so the draft cache can keep pace when
        # everything is accepted).
        (d_cache, _, _), drafts = jax.lax.scan(
            draft_step, (d_cache, y, pos_y), None, length=gamma + 1)
        d_tok = jnp.moveaxis(drafts, 0, 1)[:, :gamma]      # [B, g]
        # Target scores [y, d_1..d_gamma] in one forward.
        x_blk = jnp.concatenate([y[:, None], d_tok], axis=1)
        positions = pos_y + jnp.arange(gamma + 1, dtype=jnp.int32)
        hidden, mut = target_model.apply(
            {"params": target_params, "cache": t_cache}, x_blk,
            return_hidden=True, positions=positions,
            mutable=["cache"])
        t_cache = mut["cache"]
        logits = jnp.einsum("bsd,vd->bsv",
                            hidden.astype(jnp.float32),
                            t_embed.astype(jnp.float32))
        t_tok = jnp.argmax(logits, axis=-1).astype(
            jnp.int32)                                      # [B, g+1]
        # Longest validated prefix, synchronized to the batch min.
        match = (d_tok == t_tok[:, :gamma])
        a_slot = jnp.sum(jnp.cumprod(
            match.astype(jnp.int32), axis=1), axis=1)       # [B]
        a = jnp.min(a_slot)
        # Commit d_1..d_a plus the target's token at position a
        # (correction when a < gamma, bonus when a == gamma — same
        # formula either way).
        js = jnp.arange(gamma + 1, dtype=jnp.int32)
        d_pad = jnp.concatenate(
            [d_tok, jnp.zeros((batch, 1), jnp.int32)], axis=1)
        block = jnp.where(js[None, :] < a, d_pad, t_tok)
        out = jax.lax.dynamic_update_slice(out, block, (0, n_done))
        rewind = gamma - a
        return (_rewind_cache(t_cache, rewind),
                _rewind_cache(d_cache, rewind),
                out, n_done + a + 1, block[:, a],
                rounds + 1, proposed + gamma, accepted + a)

    def cond(state):
        return state[3] < num_tokens

    out0 = jnp.zeros((batch, cap), jnp.int32)
    (t_cache, d_cache, out, n_done, _y, rounds, proposed, accepted
     ) = jax.lax.while_loop(
        cond, round_body,
        (t_cache, d_cache, out0, jnp.int32(0), y0,
         jnp.int32(0), jnp.int32(0), jnp.int32(0)))
    tokens = jnp.concatenate([prompt, out[:, :num_tokens]], axis=1)
    stats = {"rounds": rounds, "proposed": proposed,
             "accepted": accepted}
    return tokens, stats


def make_speculative_decoder(target_config: tfm.TransformerConfig,
                             target_params,
                             draft_config: tfm.TransformerConfig,
                             draft_params, max_decode_len: int,
                             gamma: int = 4):
    """(run, target_model, draft_model) bound to decode-mode models.
    run(prompt, num_tokens) -> (tokens, stats)."""
    for name, cfg in (("target", target_config),
                      ("draft", draft_config)):
        if getattr(cfg, "kv_page_size", None):
            raise ValueError(
                f"speculative decoding needs the dense KV cache "
                f"(multi-token verify + O(1) index rewind); {name} "
                f"config sets kv_page_size={cfg.kv_page_size} — "
                f"clear it for the speculative path")
    t_model = tfm.TransformerLM(
        decode_config(target_config, max_decode_len))
    d_model = tfm.TransformerLM(
        decode_config(draft_config, max_decode_len))

    def run(prompt, num_tokens: int):
        return speculative_generate(
            t_model, target_params, d_model, draft_params, prompt,
            num_tokens, gamma=gamma)

    return run, t_model, d_model


def make_decoder(config: tfm.TransformerConfig, params,
                 max_decode_len: int):
    """Convenience: (generate_fn, model) bound to a decode-mode model
    sharing training params."""
    dconfig = decode_config(config, max_decode_len)
    model = tfm.TransformerLM(dconfig)

    def run(prompt, num_tokens, key,
            sampling: SamplingConfig = SamplingConfig()):
        cache = init_cache(model, params, prompt.shape[0])
        return generate(model, params, cache, prompt, num_tokens, key,
                        sampling)

    return run, model

"""Serving front end + load generator: HTTP ingress over the
continuous-batching engine, TTFT/TPOT measurement, Poisson load
report (VERDICT r3 order #4 — an Orca/vLLM-class engine is judged by
TTFT/TPOT under load, which needs an ingress path)."""

import json
import time
import urllib.request

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from batch_shipyard_tpu.models import inference as inf
from batch_shipyard_tpu.models import loadgen, serving
from batch_shipyard_tpu.models import transformer as tfm
from batch_shipyard_tpu.models.server import ServingFrontEnd, percentile

CFG = tfm.TransformerConfig(
    vocab_size=97, d_model=32, n_layers=2, n_heads=2, d_head=16,
    d_ff=64, max_seq_len=64, dtype=jnp.float32,
    param_dtype=jnp.float32)


@pytest.fixture(scope="module")
def params():
    model = tfm.TransformerLM(CFG)
    return model.init(jax.random.PRNGKey(7),
                      jnp.zeros((1, 8), jnp.int32))["params"]


@pytest.fixture()
def front(params):
    engine = serving.ContinuousBatcher(CFG, params, num_slots=2,
                                       max_decode_len=64)
    fe = ServingFrontEnd(engine, port=0).start()
    yield fe
    fe.shutdown()


def _post(url, payload):
    req = urllib.request.Request(
        f"{url}/v1/generate", data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"}, method="POST")
    with urllib.request.urlopen(req, timeout=120) as resp:
        return json.loads(resp.read())


def test_generate_over_http_matches_engine_greedy(front, params):
    prompt = [5, 17, 31, 2]
    out = _post(front.url, {"prompt": prompt, "max_new_tokens": 6})
    assert len(out["tokens"]) == 6
    assert out["num_tokens"] == 6
    assert out["ttft_ms"] > 0 and out["tpot_ms"] >= 0
    assert out["latency_ms"] >= out["ttft_ms"]
    # Greedy equivalence with the lockstep decoder.
    run, _ = inf.make_decoder(CFG, params, max_decode_len=64)
    ref, _ = run(jnp.asarray([prompt], jnp.int32), 6,
                 jax.random.PRNGKey(0))
    assert out["tokens"] == list(
        np.asarray(ref[0, len(prompt):]).tolist())


def test_health_stats_and_errors(front):
    with urllib.request.urlopen(f"{front.url}/healthz",
                                timeout=30) as resp:
        assert json.loads(resp.read())["ok"] is True
    _post(front.url, {"prompt": [1, 2], "max_new_tokens": 3})
    with urllib.request.urlopen(f"{front.url}/v1/stats",
                                timeout=30) as resp:
        stats = json.loads(resp.read())
    assert stats["completed_requests"] >= 1
    assert stats["generated_tokens"] >= 3
    assert set(stats["ttft_ms"]) == {"50", "90", "99"} or set(
        stats["ttft_ms"]) == {50, 90, 99}
    # Mergeable fixed-bucket histograms ride along for fleet
    # aggregation (router) — counts match the request totals.
    assert stats["ttft_hist"]["count"] == stats["completed_requests"]
    assert stats["tpot_hist"]["count"] == stats["completed_requests"]
    # Bad request -> 400, server keeps serving.
    bad = urllib.request.Request(
        f"{front.url}/v1/generate",
        data=json.dumps({"prompt": "nope"}).encode(), method="POST")
    try:
        urllib.request.urlopen(bad, timeout=30)
        assert False, "expected HTTPError"
    except urllib.error.HTTPError as exc:
        assert exc.code == 400
    out = _post(front.url, {"prompt": [3], "max_new_tokens": 2})
    assert len(out["tokens"]) == 2


def test_poisson_load_report(front):
    report = loadgen.run_load(
        front.url, num_requests=12, rate_hz=50.0,
        prompt_len=(2, 8), max_new_tokens=(2, 6), vocab_size=97,
        seed=3)
    assert report["completed"] == 12 and report["failed"] == 0
    assert report["generated_tokens"] >= 24
    assert report["tokens_per_second"] > 0
    for section in ("ttft_ms", "tpot_ms", "latency_ms"):
        assert set(report[section]) == {"p50", "p90", "p99"}
        assert report[section]["p50"] <= report[section]["p90"] <= \
            report[section]["p99"]
    hist = report["ttft_hist"]
    assert hist["count"] == 12
    assert sum(hist["counts"]) + hist["overflow"] == 12
    # Reproducible arrivals + prompts under the same seed.
    again = loadgen.run_load(
        front.url, num_requests=3, rate_hz=100.0, prompt_len=(2, 4),
        max_new_tokens=(2, 3), vocab_size=97, seed=9)
    once_more = loadgen.run_load(
        front.url, num_requests=3, rate_hz=100.0, prompt_len=(2, 4),
        max_new_tokens=(2, 3), vocab_size=97, seed=9)
    assert again["generated_tokens"] == once_more["generated_tokens"]


def test_diurnal_load_with_slo_attainment(front):
    """arrival="diurnal" replays the fleet simulator's day/night
    curve (sim/traces.diurnal_arrivals), deterministic per seed;
    slo_classes adds a per-class attainment table; shared prefix
    groups tag requests with prefix keys. Two runs at the same seed
    produce byte-identical outputs (the bench's equivalence check)."""
    from batch_shipyard_tpu.sim import traces as sim_traces

    classes = {"interactive": {"ttft_ms": 1e6, "tpot_ms": 1e6},
               "batch": {"ttft_ms": None, "tpot_ms": None}}
    kwargs = dict(num_requests=10, rate_hz=80.0, arrival="diurnal",
                  day_seconds=2.0, prompt_len=(2, 6),
                  max_new_tokens=(2, 4), vocab_size=97, seed=11,
                  shared_prefix_groups=2, shared_prefix_len=8,
                  slo_classes=classes)
    report = loadgen.run_load(front.url, **kwargs)
    assert report["completed"] == 10 and report["failed"] == 0
    assert report["shed"] == 0
    assert report["arrival"] == "diurnal"
    att = report["slo_attainment"]
    assert set(att) == {"interactive", "batch"}
    assert att["interactive"]["requests"] == 5
    # Generous targets attain fully; None targets always attain.
    assert att["interactive"]["ttft_attainment"] == 1.0
    assert att["batch"]["tpot_attainment"] == 1.0
    assert att["interactive"]["ttft_target_ms"] == 1e6
    # Deterministic replay: same seed => same arrivals, prompts, and
    # (greedy engine) token ids.
    again = loadgen.run_load(front.url, **kwargs)
    assert again["outputs_sha256"] == report["outputs_sha256"]
    assert sim_traces.diurnal_arrivals(11, 5, 2.0, 80.0, 20.0) == \
        sim_traces.diurnal_arrivals(11, 5, 2.0, 80.0, 20.0)
    with pytest.raises(ValueError):
        loadgen.run_load(front.url, num_requests=1,
                         arrival="lunar")


def test_paged_overcommit_engine_behind_front(params):
    engine = serving.ContinuousBatcher(
        CFG, params, num_slots=2, max_decode_len=64,
        kv_page_size=8, kv_num_pages=12, overcommit=True)
    fe = ServingFrontEnd(engine, port=0).start()
    try:
        report = loadgen.run_load(
            fe.url, num_requests=6, rate_hz=100.0,
            prompt_len=(2, 6), max_new_tokens=(2, 8), vocab_size=97,
            seed=1)
        assert report["completed"] == 6 and report["failed"] == 0
    finally:
        fe.shutdown()


def test_percentile_nearest_rank():
    assert percentile([], 99) == 0.0
    vals = [float(v) for v in range(1, 101)]
    assert percentile(vals, 50) == 50.0
    assert percentile(vals, 99) == 99.0


def test_streaming_generate_ndjson(front, params):
    """stream: true returns one NDJSON line per token as it decodes,
    then the final result object; tokens match the blocking path."""
    import http.client
    host, port = front.address
    conn = http.client.HTTPConnection(host, port, timeout=120)
    body = json.dumps({"prompt": [5, 17, 31, 2],
                       "max_new_tokens": 5, "stream": True})
    conn.request("POST", "/v1/generate", body=body,
                 headers={"Content-Type": "application/json"})
    resp = conn.getresponse()
    assert resp.status == 200
    assert resp.getheader("Content-Type") == "application/x-ndjson"
    lines = [json.loads(ln) for ln in
             resp.read().decode().strip().split("\n")]
    conn.close()
    token_events = [e for e in lines if "token" in e]
    final = lines[-1]
    assert [e["index"] for e in token_events] == list(
        range(len(token_events)))
    assert final["tokens"] == [e["token"] for e in token_events]
    assert final["num_tokens"] == 5
    assert final["ttft_ms"] > 0
    # Same tokens as the blocking path (greedy, same prompt).
    blocking = _post(front.url, {"prompt": [5, 17, 31, 2],
                                 "max_new_tokens": 5})
    assert blocking["tokens"] == final["tokens"]
    # Bad streaming request -> clean 400 before any stream bytes.
    conn = http.client.HTTPConnection(host, port, timeout=30)
    conn.request("POST", "/v1/generate",
                 body=json.dumps({"prompt": "bad", "stream": True}),
                 headers={"Content-Type": "application/json"})
    resp = conn.getresponse()
    assert resp.status == 400
    conn.close()


def test_streaming_engine_error_emitted_as_ndjson_line(front):
    """An engine-side rejection surfacing AFTER the chunked headers
    (e.g. prompt+generation exceeding max_decode_len) arrives as an
    {"error": ...} NDJSON line with a clean stream termination — not
    a second HTTP response corrupting the framing."""
    import http.client
    host, port = front.address
    conn = http.client.HTTPConnection(host, port, timeout=60)
    conn.request("POST", "/v1/generate",
                 body=json.dumps({"prompt": [1, 2, 3],
                                  "max_new_tokens": 100000,
                                  "stream": True}),
                 headers={"Content-Type": "application/json"})
    resp = conn.getresponse()
    assert resp.status == 200  # headers already committed
    lines = [json.loads(ln) for ln in
             resp.read().decode().strip().split("\n")]
    conn.close()
    assert len(lines) == 1 and "error" in lines[0]
    assert "max_decode_len" in lines[0]["error"]
    # Server is still healthy afterwards.
    out = _post(front.url, {"prompt": [3], "max_new_tokens": 2})
    assert len(out["tokens"]) == 2


def test_cancel_queued_and_running_requests(params):
    """DELETE /v1/requests/<id> aborts both a decoding request and a
    queued one; waiters complete with a 'cancelled' error and the
    slot frees for new work (the vLLM-class abort operation)."""
    import threading
    import time as time_mod
    import urllib.error
    engine = serving.ContinuousBatcher(CFG, params, num_slots=1,
                                       max_decode_len=64)
    fe = ServingFrontEnd(engine, port=0).start()
    try:
        # Warm the compile, then throttle the engine step so the
        # running request decodes for seconds — the cancel race is
        # deterministic regardless of CPU speed.
        _post(fe.url, {"prompt": [1], "max_new_tokens": 2})
        orig_step = engine.step

        def slow_step():
            time_mod.sleep(0.05)
            return orig_step()

        engine.step = slow_step
        results = {}

        def _gen(rid):
            try:
                results[rid] = _post(fe.url, {
                    "request_id": rid, "prompt": [2, 3],
                    "max_new_tokens": 60})
            except urllib.error.HTTPError as exc:
                results[rid] = {"status": exc.code,
                                "body": json.loads(exc.read())}

        threads = [threading.Thread(target=_gen, args=(rid,),
                                    daemon=True)
                   for rid in ("running-r", "queued-r")]
        threads[0].start()
        time_mod.sleep(0.5)  # running-r holds the single slot
        threads[1].start()
        time_mod.sleep(0.3)  # queued-r sits in the engine queue
        for rid in ("queued-r", "running-r"):
            req = urllib.request.Request(
                f"{fe.url}/v1/requests/{rid}", method="DELETE")
            with urllib.request.urlopen(req, timeout=30) as resp:
                assert resp.status == 202
        for t in threads:
            t.join(60)
        for rid in ("running-r", "queued-r"):
            out = results[rid]
            assert out.get("status") == 409 and \
                "cancelled" in out["body"]["error"], out
        engine.step = orig_step
        # Slot is free again.
        out = _post(fe.url, {"prompt": [9], "max_new_tokens": 2})
        assert len(out["tokens"]) == 2
    finally:
        fe.shutdown()


def test_serve_checkpoint_restore_roundtrip(tmp_path):
    """workloads.serve --checkpoint-dir serves trained weights: save
    params via the checkpoint module, restore-params them, and check
    array equality through the serving build path."""
    import numpy as np_mod
    from batch_shipyard_tpu.workloads import checkpoint
    model = tfm.TransformerLM(CFG)
    params = model.init(jax.random.PRNGKey(3),
                        jnp.zeros((1, 8), jnp.int32))["params"]
    import optax
    opt_state = optax.adam(1e-3).init(params)
    checkpoint.save(str(tmp_path), 7, params, opt_state)
    restored = checkpoint.restore_params(str(tmp_path))
    assert restored is not None
    rparams, step = restored
    assert step == 7
    flat = jax.tree_util.tree_leaves(params)
    rflat = jax.tree_util.tree_leaves(rparams)
    assert len(flat) == len(rflat)
    for a, b in zip(flat, rflat):
        assert np_mod.allclose(np_mod.asarray(a), np_mod.asarray(b))


def test_serve_build_slo_config(tmp_path):
    """workloads.serve --slo-config plumbing: 'default' loads the
    built-in class table, a JSON config file parses through
    config/settings.serving_slo_settings, CLI overrides win, and no
    flag means SLO scheduling stays off."""
    import argparse

    from batch_shipyard_tpu.workloads import serve as serve_mod

    ns = argparse.Namespace(slo_config="default",
                            shed_grace_ms=250.0,
                            tpot_stall_factor=None)
    slo = serve_mod.build_slo(ns)
    assert slo.shed_grace_ms == 250.0
    targets = slo.class_targets()
    assert targets["interactive"]["ttft_ms"] == 500.0
    assert targets["batch"]["ttft_ms"] is None
    cfg_file = tmp_path / "slo.json"
    cfg_file.write_text(json.dumps({"serving": {"slo": {
        "classes": [{"name": "gold", "ttft_ms": 100.0,
                     "tpot_ms": 50.0}],
        "shed_grace_ms": 100.0, "tpot_stall_factor": 2.0}}}))
    slo2 = serve_mod.build_slo(argparse.Namespace(
        slo_config=str(cfg_file), shed_grace_ms=None,
        tpot_stall_factor=None))
    assert slo2.class_targets() == {
        "gold": {"ttft_ms": 100.0, "tpot_ms": 50.0}}
    assert slo2.shed_grace_ms == 100.0
    assert slo2.tpot_stall_factor == 2.0
    assert serve_mod.build_slo(argparse.Namespace(
        slo_config=None, shed_grace_ms=None,
        tpot_stall_factor=None)) is None


def test_slo_classes_stats_and_unknown_class(params):
    """A front configured with SLO classes: responses carry the
    class, /v1/stats grows per-class attainment + engine SLO
    counters, and an unknown class is a 400."""
    engine = serving.ContinuousBatcher(CFG, params, num_slots=2,
                                       max_decode_len=64)
    classes = {"interactive": {"ttft_ms": 1e6, "tpot_ms": 1e6},
               "batch": {"ttft_ms": None, "tpot_ms": None}}
    fe = ServingFrontEnd(engine, port=0, slo_classes=classes).start()
    try:
        out = _post(fe.url, {"prompt": [1, 2], "max_new_tokens": 3,
                             "slo_class": "interactive"})
        assert out["slo_class"] == "interactive"
        _post(fe.url, {"prompt": [4], "max_new_tokens": 2})  # default
        with urllib.request.urlopen(f"{fe.url}/v1/stats",
                                    timeout=30) as resp:
            stats = json.loads(resp.read())
        slo = stats["slo"]
        row = slo["classes"]["interactive"]
        assert row["requests"] == 1 and row["ttft_attainment"] == 1.0
        assert slo["sheds"] == 0 and slo["deferrals"] >= 0
        # "standard" is not configured here: the default-class request
        # still completes and is tracked untargeted.
        assert slo["classes"]["standard"]["requests"] == 1
        try:
            _post(fe.url, {"prompt": [1], "max_new_tokens": 1,
                           "slo_class": "platinum"})
            assert False, "expected HTTPError"
        except urllib.error.HTTPError as exc:
            assert exc.code == 400
        with urllib.request.urlopen(f"{fe.url}/metrics",
                                    timeout=30) as resp:
            text = resp.read().decode()
        assert 'slo_class="interactive"' in text
    finally:
        fe.shutdown()


def test_overloaded_queue_sheds_503(params):
    """Armed shedding: a queued request whose TTFT deadline expired
    past the grace is rejected 503 with shed=true while the slot is
    held by a long decode — deepest violation first, the waiter is
    completed promptly (not at its would-be turn)."""
    import threading

    engine = serving.ContinuousBatcher(
        CFG, params, num_slots=1, max_decode_len=64,
        slo_shed_grace_ms=0.0)
    fe = ServingFrontEnd(engine, port=0).start()
    result = {}

    def _long():
        result["r"] = _post(fe.url, {"request_id": "hog",
                                     "prompt": [7, 7],
                                     "max_new_tokens": 48})

    try:
        t = threading.Thread(target=_long, daemon=True)
        t.start()
        deadline = time.monotonic() + 20
        while time.monotonic() < deadline and \
                not fe.knows("hog"):
            time.sleep(0.01)
        try:
            _post(fe.url, {"prompt": [1, 2], "max_new_tokens": 2,
                           "ttft_target_ms": 0.01})
            assert False, "expected 503 shed"
        except urllib.error.HTTPError as exc:
            assert exc.code == 503
            body = json.loads(exc.read())
            assert body["shed"] is True
            assert "shed" in body["error"]
        t.join(120)
        assert result["r"]["num_tokens"] == 48
        assert engine.slo_sheds == 1
    finally:
        fe.shutdown()


def test_loadgen_round_robins_across_replicas(params):
    """A serving fleet: run_load spreads requests across replica
    URLs and reports the per-replica completion breakdown."""
    engines = [serving.ContinuousBatcher(CFG, params, num_slots=2,
                                         max_decode_len=64)
               for _ in range(2)]
    fronts = [ServingFrontEnd(e, port=0).start() for e in engines]
    try:
        report = loadgen.run_load(
            [f.url for f in fronts], num_requests=8, rate_hz=100.0,
            prompt_len=(2, 4), max_new_tokens=(2, 4), vocab_size=97,
            seed=5)
        assert report["completed"] == 8 and report["failed"] == 0
        assert report["replicas"] == 2
        per = report["completed_by_replica"]
        assert sorted(per.values()) == [4, 4], per
        assert set(per) == {f.url for f in fronts}
    finally:
        for f in fronts:
            f.shutdown()

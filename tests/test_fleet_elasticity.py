"""Fleet-grade elasticity (ISSUE 12 / ROADMAP item 5): forcible
eviction, multi-host reshard-on-restore planning, and cross-pool gang
migration — each pinned by its seeded chaos drill, plus the unit
surfaces underneath (restore-plan math vs the real jax index maps,
the per-host Orbax restore path, the stale-request-file janitor, and
the heimdall eviction/migration exports)."""

import json
import os
import time

import pytest

from batch_shipyard_tpu.config import settings as settings_mod
from batch_shipyard_tpu.goodput import events as goodput_events
from batch_shipyard_tpu.parallel import restore_plan
from batch_shipyard_tpu.state import names


# ------------------------- restore-plan math ---------------------------

def test_shard_ranges_and_divisibility():
    assert restore_plan.shard_ranges(8, 2) == [(0, 4), (4, 8)]
    assert restore_plan.shard_ranges(6, 1) == [(0, 6)]
    with pytest.raises(ValueError):
        restore_plan.shard_ranges(8, 3)
    with pytest.raises(ValueError):
        restore_plan.shard_ranges(8, 0)


@pytest.mark.parametrize("dim,src,dst", [
    (24, 2, 1), (24, 1, 2), (24, 4, 2), (24, 2, 4), (24, 3, 4),
])
def test_host_reads_cover_target_exactly_once(dim, src, dst):
    """Every target host's reads tile its block exactly (no gap, no
    overlap), and the union of all hosts' reads covers every source
    element at least once."""
    covered_global = set()
    for m in range(dst):
        t_lo, t_hi = restore_plan.shard_ranges(dim, dst)[m]
        cursor = 0
        for read in restore_plan.host_reads(dim, src, dst, m):
            assert read.dst_lo == cursor, (m, read)
            cursor += read.hi - read.lo
            s_lo, _ = restore_plan.shard_ranges(dim, src)[read.shard]
            covered_global.update(
                range(s_lo + read.lo, s_lo + read.hi))
        assert cursor == t_hi - t_lo, f"host {m} block not tiled"
    assert covered_global == set(range(dim))


def test_read_fraction_is_one_over_m_for_even_resize():
    assert restore_plan.read_fraction(24, 2, 4, 0) == pytest.approx(
        0.25)
    assert restore_plan.read_fraction(24, 4, 1, 0) == pytest.approx(
        1.0)
    with pytest.raises(ValueError):
        restore_plan.host_reads(24, 2, 2, 5)


def test_host_restore_plan_matches_pure_math():
    """The jax-truth plan (host_restore_plan over the real
    NamedSharding index maps, with an explicit device subset playing
    one virtual host of a 2-host mesh) agrees with the pure 1-D math
    the drill probe uses — same ranges, same read fraction."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from batch_shipyard_tpu.parallel import mesh as mesh_mod
    from batch_shipyard_tpu.parallel import sharding as shard_rules
    mesh = mesh_mod.make_mesh(mesh_mod.auto_axis_sizes(4),
                              devices=jax.devices()[:4])
    x = jax.device_put(
        jax.numpy.arange(32, dtype=jax.numpy.float32).reshape(8, 4),
        NamedSharding(mesh, P(("dp", "fsdp"))))
    hosts = [jax.devices()[:2], jax.devices()[2:4]]
    for host_index, devices in enumerate(hosts):
        plan = shard_rules.host_restore_plan({"x": x},
                                             devices=devices)
        assert plan["read_fraction"] == pytest.approx(
            restore_plan.read_fraction(8, 4, 2, host_index))
        leaf = plan["leaves"][0]
        t_lo, t_hi = restore_plan.shard_ranges(8, 2)[host_index]
        covered = set()
        for (lo, hi), _cols in leaf["slices"]:
            covered.update(range(lo, hi))
        assert covered == set(range(t_lo, t_hi))
    # The full-process plan (all devices addressable — the
    # single-host case) needs everything.
    full = shard_rules.host_restore_plan({"x": x})
    assert full["read_fraction"] == pytest.approx(1.0)


def test_reshard_per_host_restore_roundtrip(tmp_path):
    """The per-host restore path (restore_args built from the TARGET
    templates' shardings — what each host of a multi-host mesh runs)
    restores a 4-device checkpoint onto a 2-device mesh bit-exactly,
    dtypes preserved, leaves laid out on the target shardings."""
    import jax
    import numpy as np
    from jax.sharding import NamedSharding, PartitionSpec as P

    from batch_shipyard_tpu.parallel import mesh as mesh_mod
    from batch_shipyard_tpu.parallel import sharding as shard_rules
    from batch_shipyard_tpu.workloads import checkpoint as ckpt_mod
    mesh4 = mesh_mod.make_mesh(mesh_mod.auto_axis_sizes(4),
                               devices=jax.devices()[:4])
    spec = P(("dp", "fsdp"))
    x = jax.device_put(
        jax.numpy.arange(32, dtype=jax.numpy.float32).reshape(8, 4),
        NamedSharding(mesh4, spec))
    kv = jax.device_put(
        (jax.numpy.arange(32) % 251 - 125).astype(
            jax.numpy.int8).reshape(8, 4),
        NamedSharding(mesh4, spec))
    ckpt_mod.save(str(tmp_path), 5, {"x": x, "kv": kv},
                  {"mu": x * 0.5})
    mesh2 = mesh_mod.make_mesh(mesh_mod.auto_axis_sizes(2),
                               devices=jax.devices()[:2])

    def target(leaf):
        return jax.device_put(
            jax.numpy.zeros(leaf.shape, leaf.dtype),
            NamedSharding(mesh2, spec))

    params_t = {"x": target(x), "kv": target(kv)}
    opt_t = {"mu": target(x)}
    restored = shard_rules.reshard_on_restore(
        str(tmp_path), params_t, opt_t, per_host=True)
    assert restored is not None
    params, opt_state, step = restored
    assert step == 5
    assert np.array_equal(np.asarray(params["x"]), np.asarray(x))
    assert params["kv"].dtype == jax.numpy.int8
    assert np.array_equal(np.asarray(params["kv"]), np.asarray(kv))
    assert np.array_equal(np.asarray(opt_state["mu"]),
                          np.asarray(x) * 0.5)
    assert params["x"].sharding.mesh.devices.size == 2


# ----------------------------- the drills ------------------------------

def test_eviction_drill_acceptance():
    """`shipyard chaos drill --evict`: uncooperative victim is
    hard-killed after grace, classified evicted (full budget,
    neutral health), resumes from the pre-notice COMMITTED barrier,
    eviction leg populated, partition exact."""
    from batch_shipyard_tpu.chaos import drill
    report = drill.run_eviction_drill(seed=1)
    invariants = report["invariants"]
    assert invariants["ok"]
    assert invariants["retries"] == 0
    assert invariants["evict_count"] >= 1
    assert invariants["resumed_from"] <= invariants["notice_step"]
    assert invariants["eviction_seconds"] > 0


def test_host_resize_drill_acceptance():
    """`shipyard chaos drill --resize`: a 2-host sharded gang loses
    a host permanently, re-forms at 1 host, restores bit-exactly
    through the per-host reshard plan, loss trajectory matching the
    oracle at every commit."""
    from batch_shipyard_tpu.chaos import drill
    report = drill.run_host_resize_drill(seed=1)
    invariants = report["invariants"]
    assert invariants["ok"]
    assert invariants["gang_size"] == 1
    assert invariants["state_bit_exact"]
    assert invariants["recorded_reads"][-2:] == \
        invariants["planned_reads"]


def test_migration_drill_acceptance():
    """`shipyard chaos drill --migrate`: total capacity loss under a
    federated gang; the elastic evaluator re-targets it onto the
    sibling pool, one trace spans the migration, the migration leg
    is priced, and the gang completes from its committed barrier."""
    from batch_shipyard_tpu.chaos import drill
    report = drill.run_migration_drill(seed=1)
    invariants = report["invariants"]
    assert invariants["ok"]
    assert invariants["trace_id_preserved"]
    assert invariants["migration_seconds"] > 0
    assert invariants["resumed_from"] > 0


# ------------------------ stale-request janitor ------------------------

def _bare_agent(store, tmp_path, pool_id="p"):
    from batch_shipyard_tpu.agent.node_agent import (
        NodeAgent, NodeIdentity)
    conf = {"pool_specification": {
        "id": pool_id, "substrate": "fake",
        "vm_configuration": {"vm_count": {"dedicated": 1}},
        "max_wait_time_seconds": 30}}
    pool = settings_mod.pool_settings(conf)
    identity = NodeIdentity(pool_id=pool_id, node_id="n0",
                            node_index=0, hostname="h",
                            internal_ip="127.0.0.1")
    return NodeAgent(store, identity, pool, work_dir=str(tmp_path))


def test_stale_preempt_file_janitor(mem_statestore, tmp_path):
    """Satellite: request files + .delivered markers of EVICTED
    (never-drained) tasks were only cleaned at next-attempt launch
    on the same node — the janitor sweep now retires them when the
    task is terminal/re-owned/gone, without touching a live task's
    pending delivery."""
    store = mem_statestore
    agent = _bare_agent(store, tmp_path)
    pk = names.task_pk("p", "j")

    def plant(task_id):
        task_dir = os.path.join(str(tmp_path), "tasks", "j", task_id)
        os.makedirs(task_dir, exist_ok=True)
        path = os.path.join(task_dir, "preempt_request.json")
        with open(path, "w", encoding="utf-8") as fh:
            fh.write(json.dumps({"requested_at": "x"}))
        with open(path + ".delivered", "w", encoding="utf-8") as fh:
            fh.write("x")
        agent._preempt_delivered.add((path, "x"))
        return path

    # Terminal task: files are garbage.
    store.insert_entity(names.TABLE_TASKS, pk, "t-done",
                        {"state": "completed", "spec": {}})
    done_path = plant("t-done")
    # Task re-owned by ANOTHER node: this node's files are garbage.
    store.insert_entity(names.TABLE_TASKS, pk, "t-moved",
                        {"state": "running", "node_id": "other",
                         "spec": {},
                         names.TASK_COL_PREEMPT_REQUEST: {
                             "requested_at": "x"}})
    moved_path = plant("t-moved")
    # Pending request on a task still owned here (delivery may be in
    # flight between claim and launch): kept.
    store.insert_entity(names.TABLE_TASKS, pk, "t-mine",
                        {"state": "running", "node_id": "n0",
                         "spec": {},
                         names.TASK_COL_PREEMPT_REQUEST: {
                             "requested_at": "x"}})
    mine_path = plant("t-mine")
    agent._last_preempt_file_sweep = 0.0
    agent._sweep_stale_preempt_files()
    assert not os.path.exists(done_path)
    assert not os.path.exists(done_path + ".delivered")
    assert not os.path.exists(moved_path)
    assert os.path.exists(mine_path)
    remaining = {k[0] for k in agent._preempt_delivered}
    assert done_path not in remaining
    assert moved_path not in remaining
    assert mine_path in remaining


def test_live_task_files_survive_janitor(mem_statestore, tmp_path):
    """A task live in _live_procs is never swept, whatever its row
    says — the kill/exit path owns its files."""
    store = mem_statestore
    agent = _bare_agent(store, tmp_path)
    pk = names.task_pk("p", "j")
    store.insert_entity(names.TABLE_TASKS, pk, "t-live",
                        {"state": "completed", "spec": {}})
    task_dir = os.path.join(str(tmp_path), "tasks", "j", "t-live")
    os.makedirs(task_dir, exist_ok=True)
    path = os.path.join(task_dir, "preempt_request.json")
    with open(path, "w", encoding="utf-8") as fh:
        fh.write("{}")
    agent._live_procs[("j", "t-live")] = object()
    agent._last_preempt_file_sweep = 0.0
    agent._sweep_stale_preempt_files()
    assert os.path.exists(path)


# ------------------------- heimdall exports ----------------------------

def test_heimdall_eviction_and_migration_exports(mem_statestore):
    """Satellite: per-pool eviction/migration counters (honoring
    NODE_GAUGE_STALE_SECONDS for node-attributed events) plus the
    eviction/migration badput-seconds gauges riding the standard
    category export."""
    from batch_shipyard_tpu.monitor import heimdall
    store = mem_statestore
    store.upsert_entity(names.TABLE_POOLS, "pools", "p1",
                        {"state": "ready"})
    now = time.time()
    store.upsert_entity(names.TABLE_NODES, "p1", "n-fresh",
                        {"state": "idle", "heartbeat_at": now})
    store.upsert_entity(names.TABLE_NODES, "p1", "n-stale",
                        {"state": "idle",
                         "heartbeat_at": now - 7 * 24 * 3600})
    goodput_events.emit(store, "p1", goodput_events.TASK_EVICTED,
                        job_id="j", task_id="t",
                        node_id="n-fresh", start=now)
    # Attributed to a long-stale node: excluded from the counter.
    goodput_events.emit(store, "p1", goodput_events.TASK_EVICTED,
                        job_id="j", task_id="t2",
                        node_id="n-stale", start=now)
    # Migrations carry no node id (the federation emits them):
    # always counted.
    goodput_events.emit(store, "p1", goodput_events.GANG_MIGRATE,
                        job_id="j", start=now - 3.0, end=now)
    lines = heimdall.build_goodput_metrics(store)
    assert 'shipyard_evictions_total{pool="p1"} 1' in lines
    assert 'shipyard_gang_migrations_total{pool="p1"} 1' in lines
    assert any(ln.startswith(
        'badput_seconds{pool="p1",category="eviction"}')
        for ln in lines)
    migration_gauge = [ln for ln in lines if ln.startswith(
        'badput_seconds{pool="p1",category="migration"}')]
    assert migration_gauge
    assert float(migration_gauge[0].rsplit(" ", 1)[1]) > 0.0

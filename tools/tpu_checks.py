"""On-chip numeric checks that cannot run in the CPU-forced CI suite.

Run from the repo root in the TPU bench environment:

    python tools/tpu_checks.py

Covers the flash-ring path (VERDICT r1 weak #3 / next #10): the
3-case rotation switch + logsumexp merge of
ops/ring_attention.ring_attention_virtual_shards — the same code the
shard_map ring body executes per rotation — against the dense oracle,
forward AND backward, at unit input scale, on the real chip.

Pallas interpret mode aborts inside shard_map on CPU, so CI covers the
building blocks in interpret mode only; this harness is the real-MXU
validation. Matmul precision is forced to 'highest' so fp32 comparisons
are meaningful (the TPU default is bf16-pass matmuls, ~1e-3 relative).
"""

from __future__ import annotations

import json
import pathlib
import sys

REPO_ROOT = pathlib.Path(__file__).resolve().parent.parent
if str(REPO_ROOT) not in sys.path:
    sys.path.insert(0, str(REPO_ROOT))

import jax

jax.config.update("jax_default_matmul_precision", "highest")

import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402


def check_flash_ring_virtual_shards() -> bool:
    from batch_shipyard_tpu.ops import attention as attn
    from batch_shipyard_tpu.ops import ring_attention as ring

    all_ok = True
    rng = np.random.RandomState(3)
    shape = (1, 512, 2, 64)  # unit scale: no atol masking
    q = jnp.asarray(rng.randn(*shape), jnp.float32)
    k = jnp.asarray(rng.randn(*shape), jnp.float32)
    v = jnp.asarray(rng.randn(*shape), jnp.float32)

    for causal in (True, False):
        for sp in (2, 4):
            def loss_ring(q, k, v):
                return jnp.sum(ring.ring_attention_virtual_shards(
                    q, k, v, sp=sp, causal=causal) ** 2)

            def loss_ref(q, k, v):
                return jnp.sum(attn.mha_reference(
                    q, k, v, causal=causal) ** 2)

            out_ring = jax.jit(
                lambda q, k, v: ring.ring_attention_virtual_shards(
                    q, k, v, sp=sp, causal=causal))(q, k, v)
            out_ref = attn.mha_reference(q, k, v, causal=causal)
            rel_f = (np.linalg.norm(np.asarray(out_ring - out_ref)) /
                     np.linalg.norm(np.asarray(out_ref)))
            g_ring = jax.jit(jax.grad(loss_ring, argnums=(0, 1, 2)))(
                q, k, v)
            g_ref = jax.jit(jax.grad(loss_ref, argnums=(0, 1, 2)))(
                q, k, v)
            rels = []
            for a, b in zip(g_ring, g_ref):
                a, b = np.asarray(a), np.asarray(b)
                rels.append(np.linalg.norm(a - b) /
                            max(np.linalg.norm(b), 1e-30))
            ok = rel_f < 1e-4 and all(r < 5e-4 for r in rels)
            print(f"flash-ring sp={sp} causal={causal}: "
                  f"fwd_rel={rel_f:.2e} "
                  f"grad_rels={[f'{r:.2e}' for r in rels]} "
                  f"{'OK' if ok else 'FAIL'}")
            all_ok = all_ok and ok
    return all_ok


def check_flash_single_chip() -> bool:
    """flash_attention (Pallas fwd+bwd kernels) vs the dense oracle on
    the real MXU — the single-chip kernel the training path runs."""
    from batch_shipyard_tpu.ops import attention as attn

    all_ok = True
    rng = np.random.RandomState(7)
    shape = (2, 1024, 4, 64)
    q = jnp.asarray(rng.randn(*shape), jnp.float32)
    k = jnp.asarray(rng.randn(*shape), jnp.float32)
    v = jnp.asarray(rng.randn(*shape), jnp.float32)
    for causal in (True, False):
        out = jax.jit(lambda q, k, v: attn.flash_attention(
            q, k, v, causal))(q, k, v)
        ref = attn.mha_reference(q, k, v, causal=causal)
        rel_f = (np.linalg.norm(np.asarray(out - ref)) /
                 np.linalg.norm(np.asarray(ref)))

        def loss_flash(q, k, v):
            return jnp.sum(attn.flash_attention(q, k, v, causal) ** 2)

        def loss_ref(q, k, v):
            return jnp.sum(
                attn.mha_reference(q, k, v, causal=causal) ** 2)

        g_fl = jax.jit(jax.grad(loss_flash, argnums=(0, 1, 2)))(
            q, k, v)
        g_rf = jax.jit(jax.grad(loss_ref, argnums=(0, 1, 2)))(q, k, v)
        rels = [np.linalg.norm(np.asarray(a - b)) /
                max(np.linalg.norm(np.asarray(b)), 1e-30)
                for a, b in zip(g_fl, g_rf)]
        ok = rel_f < 1e-4 and all(r < 5e-4 for r in rels)
        print(f"flash single-chip causal={causal}: fwd_rel={rel_f:.2e}"
              f" grad_rels={[f'{r:.2e}' for r in rels]} "
              f"{'OK' if ok else 'FAIL'}")
        all_ok = all_ok and ok
    return all_ok


def check_paged_attention() -> bool:
    """Pallas paged-decode kernel vs the XLA gather oracle with random
    block tables and ragged lengths — the serving engine's headline
    kernel, previously validated only in interpret mode (VERDICT r2
    weak #2)."""
    from batch_shipyard_tpu.ops import paged_attention as paged

    rng = np.random.RandomState(11)
    batch, heads, depth = 8, 4, 64
    page, num_pages, max_blocks = 16, 64, 8
    q = jnp.asarray(rng.randn(batch, 1, heads, depth), jnp.float32)
    k_pages = jnp.asarray(
        rng.randn(num_pages, page, heads, depth), jnp.float32)
    v_pages = jnp.asarray(
        rng.randn(num_pages, page, heads, depth), jnp.float32)
    # Distinct random pages per slot; ragged lengths incl. 1 and full.
    perm = rng.permutation(num_pages)[:batch * max_blocks]
    table = jnp.asarray(perm.reshape(batch, max_blocks), jnp.int32)
    lengths = jnp.asarray(
        [1, 5, page, page + 1, 3 * page - 2, 4 * page,
         max_blocks * page - 1, max_blocks * page], jnp.int32)
    out_k = jax.jit(paged.paged_decode_attention_kernel)(
        q, k_pages, v_pages, table, lengths)
    out_x = paged.paged_decode_attention_xla(
        q, k_pages, v_pages, table, lengths)
    rel = (np.linalg.norm(np.asarray(out_k - out_x)) /
           np.linalg.norm(np.asarray(out_x)))
    ok = rel < 1e-4
    print(f"paged-attention kernel vs xla: rel={rel:.2e} "
          f"{'OK' if ok else 'FAIL'}")
    return ok


def check_int8_matmul() -> bool:
    """quantize_int8 + int8_matmul on the real MXU: the quantized
    product must sit within the per-element quantization error bound
    of the fp32 product."""
    from batch_shipyard_tpu.ops import quantization as qz

    rng = np.random.RandomState(13)
    x = jnp.asarray(rng.randn(256, 512), jnp.float32)
    w = jnp.asarray(rng.randn(512, 384) / 22.6, jnp.float32)
    out = jax.jit(qz.quantized_linear)(x, w)
    ref = x @ w
    rel = (np.linalg.norm(np.asarray(out - ref)) /
           np.linalg.norm(np.asarray(ref)))
    # int8 per-row absmax: ~0.5/127 relative per operand; the matmul
    # contraction averages error down — 2% relative is generous.
    ok = rel < 0.02
    print(f"int8 quantized_linear vs fp32: rel={rel:.2e} "
          f"{'OK' if ok else 'FAIL'}")
    return ok


def check_fused_norm() -> bool:
    """Pallas fused RMSNorm+matmul vs the unfused XLA composition on
    the real chip (fwd; bwd is shared XLA code)."""
    from batch_shipyard_tpu.ops import fused_norm as fn

    rng = np.random.RandomState(17)
    x = jnp.asarray(rng.randn(512, 1024), jnp.float32)
    scale = jnp.asarray(1.0 + 0.1 * rng.randn(1024), jnp.float32)
    w = jnp.asarray(rng.randn(1024, 1536) / 32, jnp.float32)
    out = jax.jit(lambda x, s, w: fn.rmsnorm_matmul(
        x, s, w, impl="pallas"))(x, scale, w)
    ref = jax.jit(lambda x, s, w: fn.rmsnorm_matmul(
        x, s, w, impl="xla"))(x, scale, w)
    rel = (np.linalg.norm(np.asarray(out - ref)) /
           np.linalg.norm(np.asarray(ref)))
    ok = rel < 1e-4
    print(f"fused rmsnorm_matmul pallas vs xla: rel={rel:.2e} "
          f"{'OK' if ok else 'FAIL'}")
    return ok


# Check name -> callable; names are the KERNEL_VALIDATION.json keys
# that ops/ring_attention.resolve_ring_impl (flash_ring) and the
# silicon-proof report consume.
CHECKS = {
    "flash_single_chip": check_flash_single_chip,
    "flash_ring": check_flash_ring_virtual_shards,
    "paged_attention": check_paged_attention,
    "int8_matmul": check_int8_matmul,
    "fused_norm": check_fused_norm,
    "chunked_cross_entropy": None,  # bound below (round-5 kernel)
}


def check_chunked_cross_entropy() -> bool:
    """Pallas chunked cross-entropy vs the XLA chunked loss on the
    real chip (fwd + grad wrt hidden/embedding)."""
    from batch_shipyard_tpu.ops import chunked_loss as cl

    rng = np.random.RandomState(19)
    batch, t_len, d, vocab = 2, 256, 128, 1024
    hidden = jnp.asarray(rng.randn(batch, t_len, d), jnp.float32)
    embed = jnp.asarray(rng.randn(vocab, d) / 11.3, jnp.float32)
    targets = jnp.asarray(rng.randint(0, vocab, (batch, t_len)),
                          jnp.int32)
    targets = targets.at[0, :7].set(-1)  # exercise the ignore mask

    def loss_pl(h, e):
        return cl.chunked_softmax_xent(h, e, targets, impl="pallas")

    def loss_ref(h, e):
        return cl.chunked_softmax_xent(h, e, targets, impl="xla")

    out = jax.jit(loss_pl)(hidden, embed)
    ref = jax.jit(loss_ref)(hidden, embed)
    rel_f = abs(float(out - ref)) / max(abs(float(ref)), 1e-30)
    g_pl = jax.jit(jax.grad(loss_pl, argnums=(0, 1)))(hidden, embed)
    g_rf = jax.jit(jax.grad(loss_ref, argnums=(0, 1)))(hidden, embed)
    rels = [np.linalg.norm(np.asarray(a - b)) /
            max(np.linalg.norm(np.asarray(b)), 1e-30)
            for a, b in zip(g_pl, g_rf)]
    ok = rel_f < 1e-5 and all(r < 1e-4 for r in rels)
    print(f"chunked cross-entropy pallas vs xla: fwd_rel={rel_f:.2e} "
          f"grad_rels={[f'{r:.2e}' for r in rels]} "
          f"{'OK' if ok else 'FAIL'}")
    return ok


def check_paged_attention_int8() -> bool:
    """int8-page paged decode: the Pallas in-kernel dequant vs the
    XLA gathered-slice dequant (exact), and both vs the fp pages the
    int8 was quantized from (quantization-noise bound)."""
    from batch_shipyard_tpu.ops import paged_attention as paged
    from batch_shipyard_tpu.ops.quantization import quantize_int8_rows

    rng = np.random.RandomState(31)
    batch, heads, depth = 8, 4, 64
    page, num_pages, max_blocks = 16, 64, 8
    q = jnp.asarray(rng.randn(batch, 1, heads, depth), jnp.float32)
    k_f = jnp.asarray(
        rng.randn(num_pages, page, heads, depth), jnp.float32)
    v_f = jnp.asarray(
        rng.randn(num_pages, page, heads, depth), jnp.float32)
    kp, ks = quantize_int8_rows(k_f)
    vp, vs = quantize_int8_rows(v_f)
    perm = rng.permutation(num_pages)[:batch * max_blocks]
    table = jnp.asarray(perm.reshape(batch, max_blocks), jnp.int32)
    lengths = jnp.asarray(
        [1, 5, page, page + 1, 3 * page - 2, 4 * page,
         max_blocks * page - 1, max_blocks * page], jnp.int32)
    out_k = jax.jit(lambda *a: paged.paged_decode_attention_kernel(
        *a[:5], k_scales=a[5], v_scales=a[6]))(
        q, kp, vp, table, lengths, ks, vs)
    out_x = paged.paged_decode_attention_xla(
        q, kp, vp, table, lengths, k_scales=ks, v_scales=vs)
    ref = paged.paged_decode_attention_xla(q, k_f, v_f, table,
                                           lengths)
    rel_kx = (np.linalg.norm(np.asarray(out_k - out_x)) /
              np.linalg.norm(np.asarray(out_x)))
    rel_fp = (np.linalg.norm(np.asarray(out_x - ref)) /
              np.linalg.norm(np.asarray(ref)))
    ok = rel_kx < 1e-4 and rel_fp < 0.02
    print(f"paged-attention int8 kernel vs xla: rel={rel_kx:.2e}; "
          f"int8 vs fp pages: rel={rel_fp:.2e} "
          f"{'OK' if ok else 'FAIL'}")
    return ok


def check_int8_kv_dequant_fusion() -> bool:
    """ADVICE r5: the dense int8 KV decode path
    (models/transformer._decode_attend) dequantizes the full
    [B, T, H, D] cache with an elementwise multiply OUTSIDE any
    kernel and relies on XLA fusing it into the two attention dots.
    If the compiler materializes the dequantized k_all/v_all instead,
    peak HBM exceeds the bf16 cache the int8 path claims to halve.
    Correctness is unaffected either way — this check inspects the
    COMPILED step's buffer assignment: temp-buffer bytes must stay
    well below one dequantized cache tensor."""
    from batch_shipyard_tpu.models import inference as inf
    from batch_shipyard_tpu.models import transformer as tfm

    batch, t_len, heads, depth = 8, 2048, 4, 64
    cfg = tfm.TransformerConfig(
        vocab_size=1024, d_model=heads * depth, n_layers=1,
        n_heads=heads, d_head=depth, d_ff=512, dtype=jnp.bfloat16,
        kv_cache_dtype="int8")
    dcfg = inf.decode_config(cfg, t_len)
    model = tfm.TransformerLM(dcfg)
    params = model.init(
        jax.random.PRNGKey(0), jnp.zeros((batch, 1), jnp.int32),
        positions=jnp.zeros((1,), jnp.int32))["params"]
    cache = inf.init_cache(model, params, batch)
    tokens = jnp.zeros((batch, 1), jnp.int32)
    positions = jnp.zeros((batch,), jnp.int32)

    def step(params, cache, tokens, positions):
        logits, mutated = model.apply(
            {"params": params, "cache": cache}, tokens,
            positions=positions[:, None], mutable=["cache"])
        return logits, mutated["cache"]

    compiled = jax.jit(step).lower(params, cache, tokens,
                                   positions).compile()
    mem = compiled.memory_analysis()
    temp = getattr(mem, "temp_size_in_bytes", None)
    if temp is None:
        raise RuntimeError(
            "compiled.memory_analysis() has no temp_size_in_bytes on "
            "this backend — fusion cannot be verified")
    # One dequantized cache tensor (K or V) in bf16. A fused step's
    # temps are dominated by the [B, H, 1, T] fp32 scores (~0.25 MB
    # here); materializing even ONE full dequantized cache adds 8 MB.
    dequant_bytes = batch * t_len * heads * depth * 2
    ok = temp < dequant_bytes
    verdict = ("OK" if ok else
               "FAIL — the dense int8 path is materializing the "
               "dequantized cache")
    print(f"int8 KV dequant fusion: temp_bytes={temp} "
          f"(dequantized-cache threshold {dequant_bytes}) {verdict}")
    return ok


def check_ring_collectives() -> bool:
    """Async-DMA ring collectives (ops/ring_collectives.py): the
    virtual-ring kernels COMPILED on the chip — the same Mosaic
    DMA/semaphore lowering the multi-chip remote-copy kernels use —
    vs the dense references, and, when more than one TPU device is
    attached, the real shard_map remote-DMA ring vs the lax
    collectives. This check gates ring_attention's impl='pallas_dma'
    tier (resolve_ring_impl)."""
    from batch_shipyard_tpu.ops import ring_collectives as rc
    from batch_shipyard_tpu.parallel import mesh as mesh_mod

    all_ok = True
    rng = np.random.RandomState(23)
    for ring in (2, 4):
        x = jnp.asarray(rng.randn(ring, 128, 128), jnp.float32)
        got = jax.jit(rc.ring_all_gather_virtual)(x)
        ref = x.reshape(ring * 128, 128)
        rel_ag = max(
            float(np.linalg.norm(np.asarray(got[i]) - np.asarray(ref))
                  / np.linalg.norm(np.asarray(ref)))
            for i in range(ring))
        y = jnp.asarray(rng.randn(ring, ring * 128, 128), jnp.float32)
        got_rs = jax.jit(rc.ring_reduce_scatter_virtual)(y)
        ref_rs = jnp.sum(y, axis=0).reshape(ring, 128, 128)
        rel_rs = (np.linalg.norm(np.asarray(got_rs - ref_rs)) /
                  np.linalg.norm(np.asarray(ref_rs)))
        ok = rel_ag < 1e-6 and rel_rs < 1e-5
        print(f"ring-collectives virtual ring={ring}: "
              f"ag_rel={rel_ag:.2e} rs_rel={rel_rs:.2e} "
              f"{'OK' if ok else 'FAIL'}")
        all_ok = all_ok and ok
    n_dev = len(jax.devices())
    if n_dev > 1 and jax.default_backend() == "tpu":
        mesh = mesh_mod.make_mesh(
            mesh_mod.auto_axis_sizes(n_dev, sp=n_dev))
        x = jnp.asarray(rng.randn(n_dev * 128, 128), jnp.float32)
        got = jax.jit(lambda x: rc.ring_all_gather(x, mesh, "sp"))(x)
        rel_ag = (np.linalg.norm(np.asarray(got - x)) /
                  np.linalg.norm(np.asarray(x)))
        y = jnp.asarray(rng.randn(n_dev, n_dev * 128, 128),
                        jnp.float32)
        got_rs = jax.jit(
            lambda y: rc.ring_reduce_scatter(y, mesh, "sp"))(y)
        ref_rs = jnp.sum(y, axis=0)
        rel_rs = (np.linalg.norm(np.asarray(got_rs - ref_rs)) /
                  np.linalg.norm(np.asarray(ref_rs)))
        ok = rel_ag < 1e-6 and rel_rs < 1e-5
        print(f"ring-collectives remote-DMA ring={n_dev}: "
              f"ag_rel={rel_ag:.2e} rs_rel={rel_rs:.2e} "
              f"{'OK' if ok else 'FAIL'}")
        all_ok = all_ok and ok
    else:
        print("ring-collectives remote-DMA: skipped "
              f"({n_dev} device(s) — virtual kernels only)")
    return all_ok


def check_dense_decode_int8() -> bool:
    """In-kernel int8 dense decode (ops/decode_attention.py): the
    Pallas kernel vs the XLA dequant+einsum oracle (exact), and both
    vs the fp cache the int8 was quantized from (quantization-noise
    bound), over ragged lengths including the masked short-prefix
    region. Gates the dense decode impl='auto' kernel path."""
    from batch_shipyard_tpu.ops import decode_attention as dd
    from batch_shipyard_tpu.ops.quantization import quantize_int8_rows

    rng = np.random.RandomState(37)
    batch, t_len, heads, depth = 8, 512, 4, 64
    q = jnp.asarray(rng.randn(batch, 1, heads, depth), jnp.float32)
    k_f = jnp.asarray(rng.randn(batch, t_len, heads, depth),
                      jnp.float32)
    v_f = jnp.asarray(rng.randn(batch, t_len, heads, depth),
                      jnp.float32)
    ck, ks = quantize_int8_rows(k_f)
    cv, vs = quantize_int8_rows(v_f)
    lengths = jnp.asarray(
        [1, 5, 128, 129, 300, 511, 512, 64], jnp.int32)
    out_k = jax.jit(dd.dense_decode_attention_kernel)(
        q, ck, cv, ks, vs, lengths)
    out_x = dd.dense_decode_attention_xla(q, ck, cv, ks, vs, lengths)
    fp_scales = jnp.ones((batch, t_len, heads), jnp.float32)
    ref = dd.dense_decode_attention_xla(
        q, k_f.astype(jnp.float32), v_f, fp_scales, fp_scales,
        lengths)
    rel_kx = (np.linalg.norm(np.asarray(out_k - out_x)) /
              np.linalg.norm(np.asarray(out_x)))
    rel_fp = (np.linalg.norm(np.asarray(out_x - ref)) /
              np.linalg.norm(np.asarray(ref)))
    ok = rel_kx < 1e-4 and rel_fp < 0.02
    print(f"dense-decode int8 kernel vs xla: rel={rel_kx:.2e}; "
          f"int8 vs fp cache: rel={rel_fp:.2e} "
          f"{'OK' if ok else 'FAIL'}")
    return ok


def check_dense_decode_hlo() -> bool:
    """The 2x-HBM claim, verified not hoped: compile the dense int8
    decode step with the in-kernel impl and assert on the COMPILED
    artifact that (a) the Pallas kernel custom-call is present and
    (b) no full-cache-sized f32/bf16 dequant buffer exists anywhere
    in the HLO — HBM holds int8 + scales only."""
    import re

    from batch_shipyard_tpu.models import inference as inf
    from batch_shipyard_tpu.models import transformer as tfm

    batch, t_len, heads, depth = 8, 2048, 4, 64
    cfg = tfm.TransformerConfig(
        vocab_size=1024, d_model=heads * depth, n_layers=1,
        n_heads=heads, d_head=depth, d_ff=512, dtype=jnp.bfloat16,
        kv_cache_dtype="int8", decode_attention_impl="kernel")
    dcfg = inf.decode_config(cfg, t_len)
    model = tfm.TransformerLM(dcfg)
    params = model.init(
        jax.random.PRNGKey(0), jnp.zeros((batch, 1), jnp.int32),
        positions=jnp.zeros((1,), jnp.int32))["params"]
    cache = inf.init_cache(model, params, batch)
    tokens = jnp.zeros((batch, 1), jnp.int32)
    positions = jnp.zeros((batch,), jnp.int32)

    def step(params, cache, tokens, positions):
        logits, mutated = model.apply(
            {"params": params, "cache": cache}, tokens,
            positions=positions[:, None], mutable=["cache"])
        return logits, mutated["cache"]

    compiled = jax.jit(step).lower(params, cache, tokens,
                                   positions).compile()
    hlo = compiled.as_text()
    # The Pallas kernel must actually be in the program — match the
    # Mosaic lowering target specifically (a generic 'custom-call'
    # string also matches sharding-annotation custom-calls).
    has_kernel = ("tpu_custom_call" in hlo or "MosaicKernel" in hlo)
    cache_elems = batch * t_len * heads * depth
    dequant_buffers = []
    for dtype_name, dims in re.findall(
            r"(f32|bf16)\[([0-9,]+)\]", hlo):
        sizes = [int(d) for d in dims.split(",") if d]
        # Element count alone bounds this (no dim-count filter: a
        # reshaped 2-D materialization of the dequantized cache is
        # just as fatal as a 4-D one).
        if sizes and np.prod(sizes) >= cache_elems:
            dequant_buffers.append(f"{dtype_name}[{dims}]")
    ok = has_kernel and not dequant_buffers
    print(f"dense-decode HLO: kernel_custom_call={has_kernel} "
          f"full-cache fp buffers={sorted(set(dequant_buffers))} "
          f"{'OK' if ok else 'FAIL'}")
    return ok


CHECKS["chunked_cross_entropy"] = check_chunked_cross_entropy
CHECKS["paged_attention_int8"] = check_paged_attention_int8
CHECKS["int8_kv_dequant_fusion"] = check_int8_kv_dequant_fusion
CHECKS["ring_collectives"] = check_ring_collectives
CHECKS["dense_decode_int8"] = check_dense_decode_int8
CHECKS["dense_decode_hlo"] = check_dense_decode_hlo


def run_all(write_marker: str | None = None) -> dict:
    """Run every check, returning {name: {ok, error?, backend}}. When
    write_marker is a path, persist the results there — that file is
    the KERNEL_VALIDATION.json consumed by resolve_ring_impl, so a
    passing run flips impl='auto' rings to flash durably."""
    import traceback

    backend = jax.default_backend()
    print(f"backend={backend} devices={jax.devices()}")
    results: dict = {}
    for name, fn in CHECKS.items():
        try:
            ok = bool(fn())
            results[name] = {"ok": ok, "backend": backend}
        except Exception as exc:  # noqa: BLE001 - record, keep going
            traceback.print_exc()
            results[name] = {"ok": False, "backend": backend,
                             "error": f"{type(exc).__name__}: {exc}"}
            print(f"{name}: EXCEPTION {exc}")
    if write_marker:
        with open(write_marker, "w", encoding="utf-8") as fh:
            json.dump(results, fh, indent=2)
        print(f"wrote {write_marker}")
    n_ok = sum(1 for r in results.values() if r["ok"])
    print(f"{n_ok}/{len(results)} TPU checks OK"
          + ("" if n_ok < len(results) else " — ALL TPU CHECKS OK"))
    return results


def main(argv: list[str] | None = None) -> int:
    import argparse

    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument(
        "--write-marker", metavar="PATH", default=None,
        help="persist per-check results as KERNEL_VALIDATION.json")
    args = parser.parse_args(argv)
    results = run_all(write_marker=args.write_marker)
    return 0 if all(r["ok"] for r in results.values()) else 1


if __name__ == "__main__":
    sys.exit(main())

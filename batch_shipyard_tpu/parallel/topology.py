"""TPU topology oracle: accelerator type -> pod slice shape.

This is the TPU-native replacement for the reference's Azure vm-size
capability oracles (convoy/settings.py:717 is_gpu_pool, :749
get_gpu_type_from_vm_size, :881 is_sriov_rdma_pool, :964 temp-disk map):
given a Cloud TPU accelerator type string (e.g. ``v5litepod-16``), answer
how many worker VMs the slice has, how many chips each worker hosts, the
ICI mesh shape, and per-chip capability numbers used for scheduling and
for building `jax.sharding.Mesh` axes.

Kept deliberately table-driven so new generations are one-line additions.
"""

from __future__ import annotations

import dataclasses
import math
import re
from typing import Optional


@dataclasses.dataclass(frozen=True)
class TpuGeneration:
    name: str
    chips_per_worker: int
    cores_per_chip: int
    hbm_gib_per_chip: int
    bf16_tflops_per_chip: float
    default_ici_axis: int  # chips per ICI torus axis for default topology


# Per-generation constants (public Cloud TPU documentation values).
_GENERATIONS: dict[str, TpuGeneration] = {
    "v2": TpuGeneration("v2", 4, 2, 8, 45.0, 4),
    "v3": TpuGeneration("v3", 4, 2, 16, 123.0, 4),
    "v4": TpuGeneration("v4", 4, 2, 32, 275.0, 4),
    "v5litepod": TpuGeneration("v5litepod", 4, 1, 16, 197.0, 4),
    "v5p": TpuGeneration("v5p", 4, 2, 95, 459.0, 4),
    "v6e": TpuGeneration("v6e", 4, 1, 32, 918.0, 4),
}

# Aliases accepted in pool configs.
_ALIASES = {
    "v5e": "v5litepod",
    "v5litepod": "v5litepod",
}


@dataclasses.dataclass(frozen=True)
class TpuTopology:
    """Resolved shape of one pod slice."""

    accelerator_type: str
    generation: TpuGeneration
    num_chips: int
    num_workers: int
    chips_per_worker: int
    mesh_shape: tuple[int, ...]  # physical ICI mesh (2D or 3D torus)

    @property
    def num_cores(self) -> int:
        return self.num_chips * self.generation.cores_per_chip

    @property
    def total_hbm_gib(self) -> int:
        return self.num_chips * self.generation.hbm_gib_per_chip

    @property
    def total_bf16_tflops(self) -> float:
        return self.num_chips * self.generation.bf16_tflops_per_chip

    @property
    def is_multi_worker(self) -> bool:
        return self.num_workers > 1


def _parse_topology_string(spec: str) -> tuple[int, ...]:
    parts = spec.lower().split("x")
    try:
        dims = tuple(int(p) for p in parts)
    except ValueError as exc:
        raise ValueError(f"bad topology string {spec!r}") from exc
    if not dims or any(d < 1 for d in dims):
        raise ValueError(f"bad topology string {spec!r}")
    return dims


def _default_mesh_shape(gen: TpuGeneration, num_chips: int) -> tuple[int, ...]:
    """Default physical mesh: square-ish 2D for <=256 chips, 3D for v4/v5p
    large slices (which are 3D tori)."""
    if num_chips == 1:
        return (1, 1)
    if gen.name in ("v4", "v5p") and num_chips >= 64:
        # 3D torus: factor into near-cube of multiples of 4.
        side = round(num_chips ** (1 / 3))
        for x in range(side, 0, -1):
            if num_chips % x:
                continue
            rest = num_chips // x
            y = round(math.sqrt(rest))
            for yy in range(y, 0, -1):
                if rest % yy == 0:
                    return (x, yy, rest // yy)
        return (num_chips, 1, 1)
    # 2D torus: near-square factorization.
    x = int(math.sqrt(num_chips))
    while x > 1 and num_chips % x:
        x -= 1
    return (x, num_chips // x)


def lookup(accelerator_type: str,
           topology: Optional[str] = None) -> TpuTopology:
    """Resolve an accelerator type like ``v5litepod-16``/``v5e-16``/
    ``v4-32`` into a TpuTopology.

    Note Cloud TPU naming: v2/v3/v4/v5p types count *cores* (v4-32 = 16
    chips); v5litepod/v6e count *chips* (v5litepod-16 = 16 chips).
    """
    m = re.fullmatch(r"([a-z0-9]+)-(\d+)", accelerator_type.strip().lower())
    if not m:
        raise ValueError(
            f"unrecognized accelerator type {accelerator_type!r}")
    gen_name, count = _ALIASES.get(m.group(1), m.group(1)), int(m.group(2))
    if count < 1:
        raise ValueError(f"{accelerator_type!r}: count must be >= 1")
    if gen_name not in _GENERATIONS:
        raise ValueError(
            f"unknown TPU generation {m.group(1)!r} in "
            f"{accelerator_type!r}; known: {sorted(_GENERATIONS)}")
    gen = _GENERATIONS[gen_name]
    if gen_name in ("v2", "v3", "v4", "v5p"):
        if count % gen.cores_per_chip:
            raise ValueError(
                f"{accelerator_type}: core count not divisible by "
                f"{gen.cores_per_chip}")
        num_chips = count // gen.cores_per_chip
    else:
        num_chips = count
    if topology is not None:
        mesh_shape = _parse_topology_string(topology)
        if math.prod(mesh_shape) != num_chips:
            raise ValueError(
                f"topology {topology} does not match chip count "
                f"{num_chips} for {accelerator_type}")
    else:
        mesh_shape = _default_mesh_shape(gen, num_chips)
    # Workers host a fixed number of chips; single-chip/partial-host
    # types (e.g. v5litepod-1/-4, v2-8) are one worker.
    if num_chips > gen.chips_per_worker and (
            num_chips % gen.chips_per_worker):
        raise ValueError(
            f"{accelerator_type}: {num_chips} chips is not a multiple of "
            f"{gen.chips_per_worker} chips per worker")
    num_workers = max(1, num_chips // gen.chips_per_worker)
    chips_per_worker = num_chips if num_workers == 1 else gen.chips_per_worker
    return TpuTopology(
        accelerator_type=accelerator_type,
        generation=gen,
        num_chips=num_chips,
        num_workers=num_workers,
        chips_per_worker=chips_per_worker,
        mesh_shape=mesh_shape,
    )


def is_tpu_accelerator(accelerator_type: str) -> bool:
    try:
        lookup(accelerator_type)
        return True
    except ValueError:
        return False


# jax device_kind substrings -> generation key. Checked in order, so
# more specific strings ("v5p", "v5 lite") precede bare version
# matches. Covers the public PJRT device_kind spellings ("TPU v4",
# "TPU v5 lite", "TPU v5p", "TPU v6 lite" / "TPU v6e" aka Trillium).
_DEVICE_KIND_PATTERNS: tuple[tuple[str, str], ...] = (
    ("v5 lite", "v5litepod"),
    ("v5lite", "v5litepod"),
    ("v5e", "v5litepod"),
    ("v5p", "v5p"),
    ("v6 lite", "v6e"),
    ("v6e", "v6e"),
    ("trillium", "v6e"),
    ("v2", "v2"),
    ("v3", "v3"),
    ("v4", "v4"),
    ("v5", "v5p"),
    ("v6", "v6e"),
)


def generation_for_device_kind(device_kind: str
                               ) -> Optional[TpuGeneration]:
    """Map a jax ``device.device_kind`` string (e.g. ``"TPU v5 lite"``)
    to its generation table entry, or None for non-TPU backends (cpu
    "cpu", gpu device names). Used by bench MFU accounting to pick the
    peak-FLOPs denominator for whatever chip answered."""
    kind = device_kind.strip().lower()
    if "tpu" not in kind:
        return None
    for pattern, gen_name in _DEVICE_KIND_PATTERNS:
        if pattern in kind:
            return _GENERATIONS[gen_name]
    return None


def peak_bf16_tflops_for_device_kind(device_kind: str
                                     ) -> Optional[float]:
    """Per-chip bf16 peak TFLOP/s for a jax device_kind, or None when
    the backend is not a recognized TPU (MFU is then unreportable)."""
    gen = generation_for_device_kind(device_kind)
    return None if gen is None else gen.bf16_tflops_per_chip

"""Typed settings: the single choke-point between raw YAML dicts and code.

Capability parity with the reference's convoy/settings.py (namedtuples at
settings.py:154-527, pool_settings :1277, task_settings :3727,
credentials accessors :1745+), re-designed with frozen dataclasses and a
TPU topology oracle in place of the reference's Azure vm-size oracles
(is_gpu_pool settings.py:717, is_sriov_rdma_pool :881).

No module outside config/ should ever index into the raw config dict.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional

from batch_shipyard_tpu.parallel import topology as topo


def _get(conf: dict | None, *path: str, default: Any = None) -> Any:
    node: Any = conf
    for key in path:
        if not isinstance(node, dict) or key not in node:
            return default
        node = node[key]
    if node is None:
        return default
    return node


# -------------------------- credentials --------------------------------

@dataclasses.dataclass(frozen=True)
class GcpCredentialsSettings:
    project: str
    zone: Optional[str]
    service_account_key_file: Optional[str]
    service_account_email: Optional[str]


@dataclasses.dataclass(frozen=True)
class StorageCredentialsSettings:
    backend: str  # gcs | localfs | memory
    bucket: Optional[str]
    prefix: str
    root: Optional[str]


@dataclasses.dataclass(frozen=True)
class SshCredentialsSettings:
    username: Optional[str]
    private_key_file: Optional[str]
    public_key_file: Optional[str]


@dataclasses.dataclass(frozen=True)
class DockerRegistrySettings:
    server: str
    username: Optional[str]
    password: Optional[str]
    password_secret_id: Optional[str]


@dataclasses.dataclass(frozen=True)
class CredentialsSettings:
    gcp: Optional[GcpCredentialsSettings]
    storage: StorageCredentialsSettings
    ssh: SshCredentialsSettings
    docker_registries: tuple[DockerRegistrySettings, ...]


def credentials_settings(config: dict) -> CredentialsSettings:
    creds = _get(config, "credentials", default={})
    gcp = None
    if _get(creds, "gcp") is not None:
        gcp = GcpCredentialsSettings(
            project=_get(creds, "gcp", "project"),
            zone=_get(creds, "gcp", "zone"),
            service_account_key_file=_get(
                creds, "gcp", "service_account_key_file"),
            service_account_email=_get(creds, "gcp", "service_account_email"),
        )
    storage = StorageCredentialsSettings(
        backend=_get(creds, "storage", "backend", default="memory"),
        bucket=_get(creds, "storage", "bucket"),
        prefix=_get(creds, "storage", "prefix", default="shipyardtpu"),
        root=_get(creds, "storage", "root"),
    )
    ssh = SshCredentialsSettings(
        username=_get(creds, "ssh", "username"),
        private_key_file=_get(creds, "ssh", "private_key_file"),
        public_key_file=_get(creds, "ssh", "public_key_file"),
    )
    registries = tuple(
        DockerRegistrySettings(
            server=reg["server"],
            username=reg.get("username"),
            password=reg.get("password"),
            password_secret_id=reg.get("password_secret_id"),
        )
        for reg in _get(creds, "docker_registries", default=[])
    )
    return CredentialsSettings(
        gcp=gcp, storage=storage, ssh=ssh, docker_registries=registries)


# ---------------------------- global -----------------------------------

@dataclasses.dataclass(frozen=True)
class GlobalSettings:
    storage_entity_prefix: str
    fallback_registry: Optional[str]
    raw_output: bool
    docker_images: tuple[str, ...]
    singularity_images: tuple[str, ...]
    files: tuple[dict, ...]
    concurrent_source_downloads: int
    docker_registries: tuple["DockerRegistry", ...] = ()


@dataclasses.dataclass(frozen=True)
class DockerRegistry:
    """Private registry credentials (reference analog:
    convoy/settings.py docker_registry accessors +
    scripts/registry_login.sh — nodes log in before cascade pulls).
    ``password`` should be a secret:// ref (utils/secrets.py), which
    is stored verbatim and resolved ON NODE at login time — plaintext
    never lands in the state store. ``auth='gcloud'`` instead runs
    ``gcloud auth configure-docker <server>`` (Artifact Registry)."""
    server: str
    username: Optional[str] = None
    password: Optional[str] = None
    auth: str = "basic"           # basic | gcloud


def global_settings(config: dict) -> GlobalSettings:
    registries = []
    for entry in _get(config, "shipyard_tpu", "docker_registries",
                      default=[]) or []:
        registries.append(DockerRegistry(
            server=entry["server"],
            username=entry.get("username"),
            password=entry.get("password"),
            auth=entry.get("auth", "basic")))
    return GlobalSettings(
        storage_entity_prefix=_get(
            config, "shipyard_tpu", "storage_entity_prefix",
            default="shipyardtpu"),
        fallback_registry=_get(config, "shipyard_tpu", "fallback_registry"),
        raw_output=_get(config, "shipyard_tpu", "raw_output", default=False),
        docker_images=tuple(
            _get(config, "global_resources", "docker_images", default=[])),
        singularity_images=tuple(
            _get(config, "global_resources", "singularity_images",
                 default=[])),
        files=tuple(
            _get(config, "global_resources", "files", default=[])),
        concurrent_source_downloads=_get(
            config, "data_replication", "concurrent_source_downloads",
            default=10),
        docker_registries=tuple(registries),
    )


# ----------------------------- pool ------------------------------------

@dataclasses.dataclass(frozen=True)
class TpuPoolSettings:
    accelerator_type: str
    runtime_version: str
    topology: Optional[str]
    num_slices: int
    provisioning_model: str
    reservation_name: Optional[str]
    network: Optional[str]
    subnetwork: Optional[str]

    @property
    def info(self) -> topo.TpuTopology:
        return topo.lookup(self.accelerator_type, self.topology)

    @property
    def workers_per_slice(self) -> int:
        return self.info.num_workers

    @property
    def total_workers(self) -> int:
        return self.info.num_workers * self.num_slices

    @property
    def chips_per_worker(self) -> int:
        return self.info.chips_per_worker


@dataclasses.dataclass(frozen=True)
class AutoscaleScenarioSettings:
    name: str
    maximum_vm_count_dedicated: int
    maximum_vm_count_low_priority: int
    minimum_vm_count_dedicated: int
    minimum_vm_count_low_priority: int
    maximum_vm_increment_dedicated: int
    maximum_vm_increment_low_priority: int
    node_deallocation_option: str
    sample_lookback_interval_minutes: int
    required_sample_percentage: int
    bias_last_sample: bool
    bias_node_type: str
    rebalance_preemption_percentage: Optional[int]
    time_ranges: dict


@dataclasses.dataclass(frozen=True)
class AutoscaleSettings:
    enabled: bool
    evaluation_interval_seconds: int
    scenario: Optional[AutoscaleScenarioSettings]
    formula: Optional[str]


@dataclasses.dataclass(frozen=True)
class PoolSshSettings:
    username: str
    expiry_days: int
    generate_keypair: bool


@dataclasses.dataclass(frozen=True)
class PrometheusExporterSettings:
    enabled: bool
    port: int


@dataclasses.dataclass(frozen=True)
class PoolServicesSettings:
    """Pool-resident daemons hosted by worker 0's node agent (the
    reference runs its recurrent job manager as a job-manager task on
    the pool, cargo/recurrent_job_manager.py:187 — a recurrence keeps
    firing with no operator terminal alive)."""
    schedules: bool
    autoscale: bool
    poll_interval_seconds: float


@dataclasses.dataclass(frozen=True)
class SchedPolicySettings:
    """Pool-level scheduling-policy configuration. The knob fields
    mirror sched/policy.py ``PolicyKnobs`` ONE-TO-ONE by name
    (enforced by tests/test_names_consistency.py); None falls back to
    the PolicyKnobs default — ``sched.policy.knobs_from_settings``
    derives the knob set every consumer (agent claim path, preemption
    sweep, autoscale, fleet simulator) prices decisions with."""
    # Opt-in for warm-cache affinity deferral at claim time (the
    # claim itself is never blocked past the affinity window).
    claim_scoring: bool
    warm_cache_bonus_seconds: Optional[float]
    health_debit_seconds: Optional[float]
    backoff_debit_seconds: Optional[float]
    claim_affinity_wait_seconds: Optional[float]
    victim_warm_cost_seconds: Optional[float]
    victim_step_cost_weight: Optional[float]
    provision_seconds_per_node: Optional[float]
    avg_task_seconds: Optional[float]
    queue_tolerance_seconds: Optional[float]


@dataclasses.dataclass(frozen=True)
class SloClassSettings:
    """One serving SLO class: per-request latency targets attached at
    admission (models/serving.Request). None disables that target
    (best-effort on that axis)."""
    name: str
    ttft_ms: Optional[float]
    tpot_ms: Optional[float]


@dataclasses.dataclass(frozen=True)
class ServingSloSettings:
    """Request-level SLO scheduling configuration for the serving
    front end (models/server.py): named classes map to TTFT/TPOT
    targets, shed_grace_ms arms overload shedding in the engine, and
    tpot_stall_factor bounds admission's prefill-stall tolerance
    (models/serving.ContinuousBatcher)."""
    classes: tuple[SloClassSettings, ...]
    shed_grace_ms: Optional[float]
    tpot_stall_factor: float

    def class_targets(self) -> dict:
        """name -> {"ttft_ms": ..., "tpot_ms": ...} for the front
        end's slo_classes parameter."""
        return {c.name: {"ttft_ms": c.ttft_ms, "tpot_ms": c.tpot_ms}
                for c in self.classes}


# Default classes: interactive chat, standard API traffic, and
# untargeted batch/offline work (the class FIFO falls back to).
DEFAULT_SLO_CLASSES = (
    SloClassSettings("interactive", ttft_ms=500.0, tpot_ms=100.0),
    SloClassSettings("standard", ttft_ms=2000.0, tpot_ms=250.0),
    SloClassSettings("batch", ttft_ms=None, tpot_ms=None),
)


def serving_slo_settings(config: dict | None) -> ServingSloSettings:
    """Parse serving.slo from a config mapping; absent sections fall
    back to the default class table with shedding disarmed."""
    spec = _get(config, "serving", "slo", default={}) or {}
    entries = _get(spec, "classes")
    if entries is None:
        classes = DEFAULT_SLO_CLASSES
    else:
        classes = tuple(
            SloClassSettings(
                name=_get(entry, "name"),
                ttft_ms=_get(entry, "ttft_ms"),
                tpot_ms=_get(entry, "tpot_ms"))
            for entry in entries)
    return ServingSloSettings(
        classes=classes,
        shed_grace_ms=_get(spec, "shed_grace_ms"),
        tpot_stall_factor=_get(spec, "tpot_stall_factor",
                               default=4.0),
    )


@dataclasses.dataclass(frozen=True)
class PoolSettings:
    id: str
    substrate: str  # tpu_vm | fake | localhost
    # GCP zone override for this pool (falls back to credentials
    # gcp.zone). Federation's `location` hard constraint matches
    # against it (reference PoolConstraints.location,
    # federation/federation.py:190).
    zone: Optional[str]
    tpu: Optional[TpuPoolSettings]
    vm_size: Optional[str]
    vm_count_dedicated: int
    vm_count_low_priority: int
    task_slots_per_node: int
    inter_node_communication_enabled: bool
    container_runtimes: tuple[str, ...]
    # Docker's default runtime for task containers: 'runc' or
    # 'kata_containers' (VM-isolated containers via kata-runtime —
    # reference container_runtimes.default, schemas/pool.yaml:383 +
    # shipyard_nodeprep.sh:1105/1133).
    container_runtime_default: str
    jax_version: Optional[str]
    libtpu_version: Optional[str]
    additional_node_prep_commands: tuple[str, ...]
    reboot_on_start_task_failed: bool
    attempt_recovery_on_unusable: bool
    block_until_all_global_resources_loaded: bool
    autoscale: AutoscaleSettings
    ssh: PoolSshSettings
    environment_variables: dict
    max_wait_time_seconds: int
    # None = upload task outputs in full (streamed); a value caps each
    # output at head+tail around an explicit truncation marker.
    output_upload_cap_mb: Optional[int]
    # Task queue fan-out: >1 spreads task messages over N queues so
    # large pools (10^4+ tasks) don't serialize on one queue's lock.
    task_queue_shards: int
    node_exporter: PrometheusExporterSettings
    cadvisor: PrometheusExporterSettings
    pool_services: "PoolServicesSettings" = None  # set by parser
    sched_policy: Optional["SchedPolicySettings"] = None

    @property
    def is_tpu_pool(self) -> bool:
        """TPU analog of the reference's is_gpu_pool (settings.py:717)."""
        return self.tpu is not None

    @property
    def is_gang_capable(self) -> bool:
        """Multi-instance tasks require inter-node communication
        (reference batch.py:4616) — always true on a TPU pod slice whose
        workers share an ICI mesh."""
        return self.inter_node_communication_enabled or self.is_tpu_pool

    @property
    def current_node_count(self) -> int:
        if self.tpu is not None:
            return self.tpu.total_workers
        return self.vm_count_dedicated + self.vm_count_low_priority


def pool_settings(config: dict) -> PoolSettings:
    spec = _get(config, "pool_specification", default=None)
    if spec is None:
        raise ValueError("pool_specification is missing from pool config")
    tpu = None
    if _get(spec, "tpu") is not None:
        tpu = TpuPoolSettings(
            accelerator_type=_get(spec, "tpu", "accelerator_type"),
            runtime_version=_get(
                spec, "tpu", "runtime_version",
                default="tpu-ubuntu2204-base"),
            topology=_get(spec, "tpu", "topology"),
            num_slices=_get(spec, "tpu", "num_slices", default=1),
            provisioning_model=_get(
                spec, "tpu", "provisioning_model", default="on_demand"),
            reservation_name=_get(spec, "tpu", "reservation_name"),
            # The pool-level virtual_network block (reference
            # pool.yaml vnet) is the fallback for the tpu-level
            # network/subnetwork overrides.
            network=_get(spec, "tpu", "network") or _get(
                spec, "virtual_network", "name"),
            subnetwork=_get(spec, "tpu", "subnetwork") or _get(
                spec, "virtual_network", "subnet_name"),
        )
    scenario = None
    if _get(spec, "autoscale", "scenario") is not None:
        sc = _get(spec, "autoscale", "scenario")
        scenario = AutoscaleScenarioSettings(
            name=_get(sc, "name", default="active_tasks"),
            maximum_vm_count_dedicated=_get(
                sc, "maximum_vm_count", "dedicated", default=16),
            maximum_vm_count_low_priority=_get(
                sc, "maximum_vm_count", "low_priority", default=0),
            minimum_vm_count_dedicated=_get(
                sc, "minimum_vm_count", "dedicated", default=0),
            minimum_vm_count_low_priority=_get(
                sc, "minimum_vm_count", "low_priority", default=0),
            maximum_vm_increment_dedicated=_get(
                sc, "maximum_vm_increment_per_evaluation", "dedicated",
                default=0),
            maximum_vm_increment_low_priority=_get(
                sc, "maximum_vm_increment_per_evaluation", "low_priority",
                default=0),
            node_deallocation_option=_get(
                sc, "node_deallocation_option", default="taskcompletion"),
            sample_lookback_interval_minutes=_get(
                sc, "sample_lookback_interval_minutes", default=10),
            required_sample_percentage=_get(
                sc, "required_sample_percentage", default=70),
            bias_last_sample=_get(sc, "bias_last_sample", default=True),
            bias_node_type=_get(sc, "bias_node_type", default="auto"),
            rebalance_preemption_percentage=_get(
                sc, "rebalance_preemption_percentage"),
            time_ranges=_get(sc, "time_ranges", default={}),
        )
    autoscale = AutoscaleSettings(
        enabled=_get(spec, "autoscale", "enabled", default=False),
        evaluation_interval_seconds=_get(
            spec, "autoscale", "evaluation_interval_seconds", default=900),
        scenario=scenario,
        formula=_get(spec, "autoscale", "formula"),
    )
    sched_policy = None
    if _get(spec, "sched_policy") is not None:
        sp = _get(spec, "sched_policy")
        sched_policy = SchedPolicySettings(
            claim_scoring=_get(sp, "claim_scoring", default=False),
            warm_cache_bonus_seconds=_get(
                sp, "warm_cache_bonus_seconds"),
            health_debit_seconds=_get(sp, "health_debit_seconds"),
            backoff_debit_seconds=_get(sp, "backoff_debit_seconds"),
            claim_affinity_wait_seconds=_get(
                sp, "claim_affinity_wait_seconds"),
            victim_warm_cost_seconds=_get(
                sp, "victim_warm_cost_seconds"),
            victim_step_cost_weight=_get(
                sp, "victim_step_cost_weight"),
            provision_seconds_per_node=_get(
                sp, "provision_seconds_per_node"),
            avg_task_seconds=_get(sp, "avg_task_seconds"),
            queue_tolerance_seconds=_get(
                sp, "queue_tolerance_seconds"),
        )
    return PoolSettings(
        id=spec["id"],
        substrate=_get(spec, "substrate", default="tpu_vm"),
        zone=_get(spec, "zone"),
        tpu=tpu,
        vm_size=_get(spec, "vm_configuration", "vm_size"),
        vm_count_dedicated=_get(
            spec, "vm_configuration", "vm_count", "dedicated", default=0),
        vm_count_low_priority=_get(
            spec, "vm_configuration", "vm_count", "low_priority", default=0),
        task_slots_per_node=_get(spec, "task_slots_per_node", default=1),
        inter_node_communication_enabled=_get(
            spec, "inter_node_communication_enabled", default=False),
        container_runtimes=tuple(
            _get(spec, "container_runtimes", default=["docker"])),
        container_runtime_default=_get(
            spec, "container_runtime_default", default="runc"),
        jax_version=_get(spec, "node_prep", "jax_version"),
        libtpu_version=_get(spec, "node_prep", "libtpu_version"),
        additional_node_prep_commands=tuple(
            _get(spec, "node_prep", "additional_commands", default=[])),
        reboot_on_start_task_failed=_get(
            spec, "node_prep", "reboot_on_start_task_failed", default=False),
        attempt_recovery_on_unusable=_get(
            spec, "node_prep", "attempt_recovery_on_unusable", default=False),
        block_until_all_global_resources_loaded=_get(
            spec, "node_prep", "block_until_all_global_resources_loaded",
            default=True),
        autoscale=autoscale,
        ssh=PoolSshSettings(
            username=_get(spec, "ssh", "username", default="shipyard"),
            expiry_days=_get(spec, "ssh", "expiry_days", default=30),
            generate_keypair=_get(
                spec, "ssh", "generate_keypair", default=True),
        ),
        environment_variables=_get(
            spec, "environment_variables", default={}),
        max_wait_time_seconds=_get(
            spec, "max_wait_time_seconds", default=1800),
        output_upload_cap_mb=_get(
            spec, "output_upload_cap_mb", default=None),
        task_queue_shards=_get(
            spec, "task_queue_shards", default=1),
        node_exporter=PrometheusExporterSettings(
            enabled=_get(
                spec, "prometheus", "node_exporter", "enabled",
                default=False),
            port=_get(
                spec, "prometheus", "node_exporter", "port", default=9100),
        ),
        cadvisor=PrometheusExporterSettings(
            enabled=_get(
                spec, "prometheus", "cadvisor", "enabled", default=False),
            port=_get(spec, "prometheus", "cadvisor", "port", default=8080),
        ),
        pool_services=PoolServicesSettings(
            schedules=_get(
                spec, "pool_services", "schedules", default=False),
            autoscale=_get(
                spec, "pool_services", "autoscale", default=False),
            poll_interval_seconds=_get(
                spec, "pool_services", "poll_interval_seconds",
                default=5.0),
        ),
        sched_policy=sched_policy,
    )


# ----------------------------- jobs ------------------------------------

@dataclasses.dataclass(frozen=True)
class RecurrenceSettings:
    recurrence_interval_seconds: int
    do_not_run_until: Optional[str]
    do_not_run_after: Optional[str]
    start_window_seconds: Optional[int]
    monitor_task_completion: bool
    run_exclusive: bool


@dataclasses.dataclass(frozen=True)
class JaxDistributedSettings:
    enabled: bool
    coordinator_port: int
    transport: str  # ici | dcn | auto
    heartbeat_timeout_seconds: int


@dataclasses.dataclass(frozen=True)
class MultiInstanceSettings:
    num_instances: Any  # int | 'pool_current_dedicated' | 'pool_specification_vm_count'
    coordination_command: Optional[str]
    resource_files: tuple[dict, ...]
    jax_distributed: JaxDistributedSettings
    pytorch_xla: bool
    # Elastic gang floor: a gang that loses nodes may re-form at any
    # surviving size >= min_instances (resumed state is re-sharded
    # onto the smaller mesh by parallel/sharding.reshard_on_restore).
    # None = rigid gang (the historical contract): all-or-nothing.
    min_instances: Optional[int] = None

    def resolve_num_instances(self, pool: PoolSettings) -> int:
        if isinstance(self.num_instances, int):
            return self.num_instances
        if self.num_instances in (
                "pool_current_dedicated", "pool_specification_vm_count",
                "pool_current_low_priority"):
            return pool.current_node_count
        raise ValueError(
            f"cannot resolve num_instances {self.num_instances!r}")


@dataclasses.dataclass(frozen=True)
class TaskSettings:
    id: Optional[str]
    docker_image: Optional[str]
    singularity_image: Optional[str]
    runtime: str  # docker | singularity | none
    command: str
    environment_variables: dict
    tpu: bool
    gpus: int
    depends_on: tuple[str, ...]
    depends_on_range: Optional[tuple[int, int]]
    max_task_retries: int
    max_wall_time_seconds: Optional[int]
    # Numeric scheduling priority WITHIN the job's queue band: the
    # preempt sweep compares these to elect victims (higher pending
    # beats lower running). Defaults to the job's priority.
    priority: int
    # Wedge watchdog opt-in: kill + requeue the task when it emits no
    # progress beat ($SHIPYARD_PROGRESS_FILE) for this long.
    progress_deadline_seconds: Optional[int]
    # Compile-cache identity digest (compilecache/manager.py
    # identity_key) this task's program compiles under. Advisory
    # placement hint: the claim path's warm-cache affinity policy
    # (sched/policy.py) prefers nodes whose persistent cache already
    # holds this identity; exported as
    # $SHIPYARD_COMPILE_CACHE_IDENTITY for the workload to enable the
    # cache with.
    compile_cache_identity: Optional[str]
    retention_time_seconds: Optional[int]
    multi_instance: Optional[MultiInstanceSettings]
    input_data: tuple[dict, ...]
    output_data: tuple[dict, ...]
    resource_files: tuple[dict, ...]
    remove_container_after_exit: bool
    shm_size: Optional[str]
    additional_docker_run_options: tuple[str, ...]
    additional_singularity_options: tuple[str, ...]
    task_factory: Optional[dict]
    merge_task: bool
    default_exit_options: dict

    @property
    def image(self) -> Optional[str]:
        return self.docker_image or self.singularity_image

    @property
    def is_multi_instance(self) -> bool:
        return self.multi_instance is not None


@dataclasses.dataclass(frozen=True)
class JobSettings:
    id: str
    pool_id: Optional[str]
    auto_complete: bool
    priority: int
    max_task_retries: int
    max_wall_time_seconds: Optional[int]
    allow_run_on_missing_image: bool
    environment_variables: dict
    # secret:// ref whose resolved value is a JSON/YAML map of extra
    # env vars, resolved ON NODE at task launch (the reference's
    # environment_variables_keyvault_secret_id, keyvault.py:176 —
    # whole env blocks ride KeyVault, never the state store).
    environment_variables_secret_id: Optional[str]
    recurrence: Optional[RecurrenceSettings]
    job_preparation_command: Optional[str]
    job_release_command: Optional[str]
    # Per-job scratch space with job lifetime (the reference's BeeOND
    # auto_scratch analog, settings.py:1496/batch.py:4949 — there a
    # distributed FS across job nodes; here node-local NVMe scratch
    # at SHIPYARD_JOB_SCRATCH, created at job prep and removed at job
    # release; cross-node sharing rides gcsfuse/fs clusters instead).
    auto_scratch: bool
    input_data: tuple[dict, ...]
    tasks: tuple[dict, ...]  # raw task dicts (expanded by task factories)
    merge_task: Optional[dict]
    federation_constraints: dict
    # auto_pool: {"keep_alive": bool} — the job provisions its own
    # pool (derived from the configured pool spec) and the reaper
    # tears it down when the job completes (reference
    # _construct_auto_pool_specification, fleet.py:1768).
    auto_pool: Optional[dict]
    # Server-side task-factory expansion: submit the generator spec
    # as ONE expansion row and let the pool's leader-gated expander
    # (jobs/expansion.py) materialize task rows + queue messages —
    # the client round-trips O(1) instead of O(tasks). Requires every
    # task to carry a task_factory (there is no per-task payload to
    # ship otherwise).
    server_side_expansion: bool = False


def job_settings_list(config: dict) -> list[JobSettings]:
    jobs = _get(config, "job_specifications", default=None)
    if jobs is None:
        raise ValueError("job_specifications is missing from jobs config")
    return [_job_settings(j) for j in jobs]


def _job_settings(job: dict) -> JobSettings:
    recurrence = None
    if _get(job, "recurrence") is not None:
        recurrence = RecurrenceSettings(
            recurrence_interval_seconds=_get(
                job, "recurrence", "schedule",
                "recurrence_interval_seconds"),
            do_not_run_until=_get(
                job, "recurrence", "schedule", "do_not_run_until"),
            do_not_run_after=_get(
                job, "recurrence", "schedule", "do_not_run_after"),
            start_window_seconds=_get(
                job, "recurrence", "schedule", "start_window_seconds"),
            monitor_task_completion=_get(
                job, "recurrence", "job_manager", "monitor_task_completion",
                default=False),
            run_exclusive=_get(
                job, "recurrence", "job_manager", "run_exclusive",
                default=False),
        )
    return JobSettings(
        id=job["id"],
        pool_id=_get(job, "pool_id"),
        auto_complete=_get(job, "auto_complete", default=False),
        priority=_get(job, "priority", default=0),
        max_task_retries=_get(job, "max_task_retries", default=0),
        max_wall_time_seconds=_get(job, "max_wall_time_seconds"),
        allow_run_on_missing_image=_get(
            job, "allow_run_on_missing_image", default=False),
        environment_variables=_get(
            job, "environment_variables", default={}),
        environment_variables_secret_id=_get(
            job, "environment_variables_keyvault_secret_id"),
        recurrence=recurrence,
        job_preparation_command=_get(job, "job_preparation", "command"),
        job_release_command=_get(job, "job_release", "command"),
        auto_scratch=_get(job, "auto_scratch", default=False),
        input_data=tuple(_get(job, "input_data", default=[])),
        tasks=tuple(_get(job, "tasks", default=[])),
        merge_task=_get(job, "merge_task"),
        federation_constraints=_get(
            job, "federation_constraints", default={}),
        auto_pool=_get(job, "auto_pool"),
        server_side_expansion=_get(job, "server_side_expansion",
                                   default=False),
    )


def job_settings_to_raw(job: JobSettings) -> dict:
    """Invert ``_job_settings``: a raw job dict that parses back to an
    equal JobSettings. This is what the server-side expansion row
    stores — the expander re-derives the full settings pool-side from
    one JSON-serializable dict, so the wire format stays the config
    schema itself rather than a second pickled shape."""
    raw: dict = {
        "id": job.id,
        "pool_id": job.pool_id,
        "auto_complete": job.auto_complete,
        "priority": job.priority,
        "max_task_retries": job.max_task_retries,
        "max_wall_time_seconds": job.max_wall_time_seconds,
        "allow_run_on_missing_image": job.allow_run_on_missing_image,
        "environment_variables": dict(job.environment_variables),
        "auto_scratch": job.auto_scratch,
        "input_data": [dict(d) for d in job.input_data],
        "tasks": [dict(t) for t in job.tasks],
        "merge_task": job.merge_task,
        "federation_constraints": dict(job.federation_constraints),
        "auto_pool": job.auto_pool,
        "server_side_expansion": job.server_side_expansion,
    }
    if job.environment_variables_secret_id is not None:
        raw["environment_variables_keyvault_secret_id"] = \
            job.environment_variables_secret_id
    if job.job_preparation_command is not None:
        raw["job_preparation"] = {
            "command": job.job_preparation_command}
    if job.job_release_command is not None:
        raw["job_release"] = {"command": job.job_release_command}
    if job.recurrence is not None:
        rec = job.recurrence
        raw["recurrence"] = {
            "schedule": {
                "recurrence_interval_seconds":
                    rec.recurrence_interval_seconds,
                "do_not_run_until": rec.do_not_run_until,
                "do_not_run_after": rec.do_not_run_after,
                "start_window_seconds": rec.start_window_seconds,
            },
            "job_manager": {
                "monitor_task_completion":
                    rec.monitor_task_completion,
                "run_exclusive": rec.run_exclusive,
            },
        }
    return raw


def task_settings(task: dict, job: JobSettings,
                  pool: PoolSettings | None = None) -> TaskSettings:
    """Merge pool/job/task layers into final task settings.

    Reference analog: settings.task_settings (settings.py:3727) which
    merges pool+job+task config, resolves images and run options.
    """
    env = dict(pool.environment_variables) if pool is not None else {}
    env.update(job.environment_variables)
    env.update(_get(task, "environment_variables", default={}))
    runtime = _get(task, "runtime")
    docker_image = _get(task, "docker_image")
    singularity_image = _get(task, "singularity_image")
    if runtime is None:
        if docker_image:
            runtime = "docker"
        elif singularity_image:
            runtime = "singularity"
        else:
            runtime = "none"
    if docker_image and singularity_image:
        raise ValueError(
            "task may not specify both docker_image and singularity_image")
    mi = None
    if _get(task, "multi_instance") is not None:
        raw_mi = _get(task, "multi_instance")
        mi = MultiInstanceSettings(
            num_instances=_get(raw_mi, "num_instances", default=1),
            min_instances=_get(raw_mi, "min_instances"),
            coordination_command=_get(raw_mi, "coordination_command"),
            resource_files=tuple(
                _get(raw_mi, "resource_files", default=[])),
            jax_distributed=JaxDistributedSettings(
                enabled=_get(
                    raw_mi, "jax_distributed", "enabled", default=True),
                coordinator_port=_get(
                    raw_mi, "jax_distributed", "coordinator_port",
                    default=8476),
                transport=_get(
                    raw_mi, "jax_distributed", "transport", default="auto"),
                heartbeat_timeout_seconds=_get(
                    raw_mi, "jax_distributed", "heartbeat_timeout_seconds",
                    default=100),
            ),
            pytorch_xla=_get(raw_mi, "pytorch_xla", "enabled", default=False),
        )
    depends_on_range = None
    if _get(task, "depends_on_range") is not None:
        rng = _get(task, "depends_on_range")
        depends_on_range = (rng[0], rng[1])
    return TaskSettings(
        id=_get(task, "id"),
        docker_image=docker_image,
        singularity_image=singularity_image,
        runtime=runtime,
        command=_get(task, "command", default=""),
        environment_variables=env,
        tpu=_get(task, "tpu", default=(
            pool.is_tpu_pool if pool is not None else False)),
        gpus=_get(task, "gpus", default=0),
        depends_on=tuple(_get(task, "depends_on", default=[])),
        depends_on_range=depends_on_range,
        max_task_retries=_get(
            task, "max_task_retries", default=job.max_task_retries),
        max_wall_time_seconds=_get(
            task, "max_wall_time_seconds", default=job.max_wall_time_seconds),
        priority=_get(task, "priority", default=job.priority),
        progress_deadline_seconds=_get(task,
                                       "progress_deadline_seconds"),
        compile_cache_identity=_get(task, "compile_cache_identity"),
        retention_time_seconds=_get(task, "retention_time_seconds"),
        multi_instance=mi,
        input_data=tuple(_get(task, "input_data", default=[])),
        output_data=tuple(_get(task, "output_data", default=[])),
        resource_files=tuple(_get(task, "resource_files", default=[])),
        remove_container_after_exit=_get(
            task, "remove_container_after_exit", default=True),
        shm_size=_get(task, "shm_size"),
        additional_docker_run_options=tuple(
            _get(task, "additional_docker_run_options", default=[])),
        additional_singularity_options=tuple(
            _get(task, "additional_singularity_options", default=[])),
        task_factory=_get(task, "task_factory"),
        merge_task=_get(task, "merge_task", default=False),
        default_exit_options=_get(
            task, "exit_conditions", "default", "exit_options", default={}),
    )

"""Int8 quantization kernel tests (interpret mode): round-trip error
bounds, unbiasedness of stochastic rounding, matmul accuracy, QAT
gradients."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.experimental.pallas import tpu as pltpu

from batch_shipyard_tpu.ops import quantization as q


@pytest.fixture(autouse=True)
def interpret_mode():
    with pltpu.force_tpu_interpret_mode():
        yield


def test_quantize_roundtrip_error_bound():
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randn(64, 128), jnp.float32)
    values, scales = q.quantize_int8(x, seed=1)
    assert values.dtype == jnp.int8
    recon = q.dequantize_int8(values, scales)
    # Error bounded by one quantization step per element.
    step = np.asarray(scales)
    err = np.abs(np.asarray(recon) - np.asarray(x))
    assert (err <= step + 1e-6).all()


def test_stochastic_rounding_unbiased():
    # A constant halfway between two int8 steps: the mean of many
    # stochastic roundings approaches the true value.
    x = jnp.full((8, 128), 0.5, jnp.float32)
    totals = []
    for seed in range(20):
        values, scales = q.quantize_int8(x, seed=seed)
        totals.append(float(jnp.mean(q.dequantize_int8(values,
                                                       scales))))
    assert abs(np.mean(totals) - 0.5) < 0.02


def test_int8_matmul_accuracy():
    rng = np.random.RandomState(1)
    x = jnp.asarray(rng.randn(32, 64), jnp.float32)
    w = jnp.asarray(rng.randn(64, 48), jnp.float32)
    exact = np.asarray(x) @ np.asarray(w)
    got = np.asarray(q.quantized_linear(x, w, 3))
    # int8 x int8 with stochastic rounding: ~3% mean relative error
    # for gaussian operands at K=64 (stochastic rounding trades bias
    # for ~2x the variance of nearest rounding).
    denom = np.maximum(np.abs(exact), 1.0)
    assert (np.abs(got - exact) / denom).mean() < 0.05


def test_quantized_linear_gradients_full_precision():
    rng = np.random.RandomState(2)
    x = jnp.asarray(rng.randn(16, 32), jnp.float32)
    w = jnp.asarray(rng.randn(32, 24), jnp.float32)

    def loss_q(x, w):
        return jnp.sum(q.quantized_linear(x, w, 0) ** 2)

    gx, gw = jax.grad(loss_q, argnums=(0, 1))(x, w)
    # Straight-through backward: compare against the dense-matmul
    # gradient of the QUANTIZED forward output: d/dx sum(y^2) = 2 y w^T
    y = q.quantized_linear(x, w, 0)
    np.testing.assert_allclose(np.asarray(gx),
                               np.asarray(2 * y @ w.T), rtol=1e-5)
    np.testing.assert_allclose(np.asarray(gw),
                               np.asarray(2 * x.T @ y), rtol=1e-5)

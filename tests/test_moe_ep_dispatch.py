"""Explicit expert-parallel MoE dispatch over the hierarchical
all-to-all (ROADMAP 'shard_map MoE dispatch variant'): equivalence
with the dense einsum formulation on a factored 2x4 ep mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from flax import linen as nn
from jax import shard_map
from jax.sharding import Mesh, PartitionSpec as P

from batch_shipyard_tpu.models import moe

E, D, F = 8, 64, 128          # experts, d_model, d_ff
G_LOCAL = 16                  # tokens per device group
CAP = 4


def _mesh():
    devices = np.array(jax.devices()[:8]).reshape(2, 4)
    return Mesh(devices, ("ep_out", "ep_in"))


def _weights(seed=0):
    rng = np.random.RandomState(seed)
    return (
        jnp.asarray(rng.randn(D, E) / 8, jnp.float32),       # router
        jnp.asarray(rng.randn(E, D, F) / 8, jnp.float32),    # gate
        jnp.asarray(rng.randn(E, D, F) / 8, jnp.float32),    # up
        jnp.asarray(rng.randn(E, F, D) / 11, jnp.float32),   # down
    )


def _dense_group(flat_g, router, w_gate, w_up, w_down, routing,
                 num_selected=2):
    """The einsum formulation on ONE device group with FULL expert
    weights — the oracle for the distributed exchange."""
    logits = flat_g.astype(jnp.float32) @ router
    if routing == "expert_choice":
        dispatch, combine, aux = moe.expert_choice_routing(logits, CAP)
    elif routing == "topk":
        dispatch, combine, aux = moe.topk_routing(logits, CAP,
                                                  num_selected)
    else:
        dispatch, combine, aux = moe.top1_routing(logits, CAP)
    expert_in = jnp.einsum("gec,gd->ecd", dispatch, flat_g)
    gate_act = jnp.einsum("ecd,edf->ecf", expert_in, w_gate)
    up_act = jnp.einsum("ecd,edf->ecf", expert_in, w_up)
    out = jnp.einsum("ecf,efd->ecd", nn.silu(gate_act) * up_act,
                     w_down)
    return jnp.einsum("gec,ecd->gd", combine, out), aux


@pytest.mark.parametrize("routing", ["top1", "topk",
                                     "expert_choice"])
def test_hierarchical_ep_dispatch_matches_dense(routing):
    mesh = _mesh()
    router, w_gate, w_up, w_down = _weights()
    rng = np.random.RandomState(3)
    tokens = jnp.asarray(rng.randn(8 * G_LOCAL, D), jnp.float32)

    def body(flat, router, wg, wu, wd):
        return moe.moe_ep_apply_shard(
            flat, router, wg, wu, wd, capacity=CAP,
            outer_axis="ep_out", inner_axis="ep_in",
            routing=routing, dtype=jnp.float32)

    ep = ("ep_out", "ep_in")
    fn = shard_map(
        body, mesh=mesh,
        in_specs=(P(ep, None), P(None, None), P(ep, None, None),
                  P(ep, None, None), P(ep, None, None)),
        out_specs=(P(ep, None), P()),
        check_vma=False)
    got, aux = jax.jit(fn)(tokens, router, w_gate, w_up, w_down)

    want = []
    want_aux = []
    for g in range(8):
        y, a = _dense_group(tokens[g * G_LOCAL:(g + 1) * G_LOCAL],
                            router, w_gate, w_up, w_down, routing)
        want.append(y)
        want_aux.append(a)
    want = jnp.concatenate(want, axis=0)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)
    np.testing.assert_allclose(float(aux),
                               float(np.mean(want_aux)), rtol=1e-5)


def test_hierarchical_ep_dispatch_differentiable():
    """The exchange is an involution of transposable collectives, so
    the whole body must be trainable end to end."""
    mesh = _mesh()
    router, w_gate, w_up, w_down = _weights(seed=5)
    rng = np.random.RandomState(7)
    tokens = jnp.asarray(rng.randn(8 * G_LOCAL, D), jnp.float32)
    ep = ("ep_out", "ep_in")

    def loss(params, flat):
        def body(flat, router, wg, wu, wd):
            y, aux = moe.moe_ep_apply_shard(
                flat, router, wg, wu, wd, capacity=CAP,
                outer_axis="ep_out", inner_axis="ep_in",
                dtype=jnp.float32)
            return jnp.sum(y ** 2)[None] + 0.01 * aux[None]

        fn = shard_map(
            body, mesh=mesh,
            in_specs=(P(ep, None), P(None, None),
                      P(ep, None, None), P(ep, None, None),
                      P(ep, None, None)),
            out_specs=P(ep),
            check_vma=False)
        return jnp.sum(fn(flat, *params))

    grads = jax.jit(jax.grad(loss))((router, w_gate, w_up, w_down),
                                    tokens)
    for g in grads:
        arr = np.asarray(g)
        assert np.all(np.isfinite(arr))
        assert np.abs(arr).sum() > 0

def test_single_axis_ep_dispatch_matches_dense():
    """outer_axis=None: the exchange degenerates to one all_to_all
    over a single 8-way ep axis — same per-group outputs."""
    mesh = Mesh(np.array(jax.devices()[:8]), ("ep",))
    router, w_gate, w_up, w_down = _weights(seed=11)
    rng = np.random.RandomState(13)
    tokens = jnp.asarray(rng.randn(8 * G_LOCAL, D), jnp.float32)

    def body(flat, router, wg, wu, wd):
        return moe.moe_ep_apply_shard(
            flat, router, wg, wu, wd, capacity=CAP,
            outer_axis=None, inner_axis="ep", dtype=jnp.float32)

    fn = shard_map(
        body, mesh=mesh,
        in_specs=(P("ep", None), P(None, None), P("ep", None, None),
                  P("ep", None, None), P("ep", None, None)),
        out_specs=(P("ep", None), P()),
        check_vma=False)
    got, aux = jax.jit(fn)(tokens, router, w_gate, w_up, w_down)
    outs, auxes = zip(*[
        _dense_group(tokens[g * G_LOCAL:(g + 1) * G_LOCAL],
                     router, w_gate, w_up, w_down, "top1")
        for g in range(8)])
    want = jnp.concatenate(outs, axis=0)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)
    np.testing.assert_allclose(float(aux), float(np.mean(auxes)),
                               rtol=1e-5)

"""HTTP serving front end over the continuous-batching engine.

The reference has no serving story; SURVEY.md treats recipes as the
acceptance surface, and an Orca/vLLM-class engine is judged by
TTFT/TPOT under load — which needs an ingress path. This front end is
deliberately stdlib-only (http.server): the engine's throughput comes
from the jitted decode step, not the socket layer, and one thread per
in-flight request is plenty for a per-replica slot count.

Architecture:
  - HTTP handlers parse/validate and enqueue (request, Event) pairs;
  - ONE engine thread owns the ContinuousBatcher: it drains the
    submission queue, calls engine.step() while work is active, and
    completes waiters — the engine is never touched from two threads;
  - the engine's on_token hook timestamps each request's first token,
    giving true TTFT (time-to-first-token) rather than
    time-to-completion.

Endpoints:
  POST /v1/generate   {"prompt": [ids], "max_new_tokens": n,
                       "request_id"?: str, "eos_id"?: int}
      -> {"request_id", "tokens", "num_tokens", "ttft_ms",
          "tpot_ms", "latency_ms"}
  GET  /v1/stats      aggregate counters + latency percentiles
  GET  /healthz       liveness
"""

from __future__ import annotations

import json
import math
import queue
import threading
import time
import uuid
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

from batch_shipyard_tpu.models.serving import ContinuousBatcher, Request
from batch_shipyard_tpu.utils import util

logger = util.get_logger(__name__)


class _Pending:
    __slots__ = ("request", "event", "submitted_at", "first_token_at",
                 "finished_at", "tokens", "error")

    def __init__(self, request: Request) -> None:
        self.request = request
        self.event = threading.Event()
        self.submitted_at = time.perf_counter()
        self.first_token_at: Optional[float] = None
        self.finished_at: Optional[float] = None
        self.tokens: Optional[list[int]] = None
        self.error: Optional[str] = None


def percentile(values: list[float], pct: float) -> float:
    """Nearest-rank percentile (no numpy dependency in the serving
    path)."""
    if not values:
        return 0.0
    ordered = sorted(values)
    k = max(1, min(len(ordered),
                   math.ceil(pct / 100.0 * len(ordered))))
    return ordered[k - 1]


class ServingFrontEnd:
    """Owns the engine thread + HTTP server around a
    ContinuousBatcher."""

    def __init__(self, engine: ContinuousBatcher,
                 host: str = "127.0.0.1", port: int = 0) -> None:
        self.engine = engine
        engine.on_token = self._on_token
        self._submit_q: "queue.Queue[_Pending]" = queue.Queue()
        self._inflight: dict[str, _Pending] = {}
        self._inflight_lock = threading.Lock()
        self._stop = threading.Event()
        self._stats_lock = threading.Lock()
        self._completed: list[dict] = []
        self._started_at = time.perf_counter()
        self._engine_thread = threading.Thread(
            target=self._engine_loop, name="serving-engine", daemon=True)
        front = self

        class Handler(BaseHTTPRequestHandler):
            # Silence per-request stderr logging.
            def log_message(self, fmt, *args):  # noqa: N802
                pass

            def _reply(self, code: int, payload: dict) -> None:
                body = json.dumps(payload).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):  # noqa: N802
                if self.path == "/healthz":
                    self._reply(200, {"ok": True})
                elif self.path == "/v1/stats":
                    self._reply(200, front.stats())
                else:
                    self._reply(404, {"error": "not found"})

            def do_POST(self):  # noqa: N802
                if self.path != "/v1/generate":
                    self._reply(404, {"error": "not found"})
                    return
                try:
                    length = int(self.headers.get("Content-Length", 0))
                    spec = json.loads(self.rfile.read(length))
                    result = front.generate(spec)
                except ValueError as exc:
                    self._reply(400, {"error": str(exc)})
                    return
                except Exception as exc:  # defensive: keep serving
                    logger.exception("generate failed")
                    self._reply(500, {"error": str(exc)})
                    return
                self._reply(200, result)

        self._httpd = ThreadingHTTPServer((host, port), Handler)
        self._http_thread = threading.Thread(
            target=self._httpd.serve_forever, name="serving-http",
            daemon=True)

    # ------------------------------ lifecycle --------------------------

    @property
    def address(self) -> tuple[str, int]:
        return self._httpd.server_address[:2]

    @property
    def url(self) -> str:
        host, port = self.address
        return f"http://{host}:{port}"

    def start(self) -> "ServingFrontEnd":
        self._engine_thread.start()
        self._http_thread.start()
        return self

    def shutdown(self) -> None:
        self._stop.set()
        self._httpd.shutdown()
        self._httpd.server_close()
        self._engine_thread.join(timeout=10.0)

    # ------------------------------ serving ----------------------------

    def generate(self, spec: dict, timeout: float = 300.0) -> dict:
        """Blocking generate: enqueue to the engine thread, wait for
        completion, return tokens + latency breakdown."""
        prompt = spec.get("prompt")
        if not isinstance(prompt, list) or not all(
                isinstance(t, int) for t in prompt):
            raise ValueError("prompt must be a list of token ids")
        request_id = str(spec.get("request_id") or uuid.uuid4().hex[:12])
        request = Request(
            request_id=request_id, prompt=prompt,
            max_new_tokens=int(spec.get("max_new_tokens", 16)),
            eos_id=spec.get("eos_id"))
        pending = _Pending(request)
        with self._inflight_lock:
            if request_id in self._inflight:
                raise ValueError(f"request_id {request_id} in flight")
            self._inflight[request_id] = pending
        self._submit_q.put(pending)
        try:
            if not pending.event.wait(timeout):
                raise TimeoutError(
                    f"request {request_id} timed out after {timeout}s")
        finally:
            with self._inflight_lock:
                self._inflight.pop(request_id, None)
        if pending.error is not None:
            raise ValueError(pending.error)
        n = len(pending.tokens)
        ttft = (pending.first_token_at or pending.finished_at) - \
            pending.submitted_at
        decode = pending.finished_at - (pending.first_token_at or
                                        pending.submitted_at)
        tpot = decode / max(1, n - 1)
        result = {
            "request_id": request_id,
            "tokens": pending.tokens,
            "num_tokens": n,
            "ttft_ms": ttft * 1e3,
            "tpot_ms": tpot * 1e3,
            "latency_ms": (pending.finished_at -
                           pending.submitted_at) * 1e3,
        }
        with self._stats_lock:
            self._completed.append({
                "ttft_ms": result["ttft_ms"],
                "tpot_ms": result["tpot_ms"],
                "latency_ms": result["latency_ms"],
                "num_tokens": n,
            })
        return result

    def stats(self) -> dict:
        with self._stats_lock:
            done = list(self._completed)
        elapsed = time.perf_counter() - self._started_at
        tokens = sum(r["num_tokens"] for r in done)
        ttfts = [r["ttft_ms"] for r in done]
        tpots = [r["tpot_ms"] for r in done]
        return {
            "completed_requests": len(done),
            "generated_tokens": tokens,
            "uptime_seconds": elapsed,
            "tokens_per_second": tokens / elapsed if elapsed else 0.0,
            "ttft_ms": {p: percentile(ttfts, p) for p in (50, 95, 99)},
            "tpot_ms": {p: percentile(tpots, p) for p in (50, 95, 99)},
        }

    # --------------------------- engine thread -------------------------

    def _on_token(self, request_id: str, token: int, index: int) -> None:
        if index == 0:
            with self._inflight_lock:
                pending = self._inflight.get(request_id)
            if pending is not None and pending.first_token_at is None:
                pending.first_token_at = time.perf_counter()

    def _engine_loop(self) -> None:
        while not self._stop.is_set():
            # Park only when fully idle; with active slots the loop
            # must spin at full decode rate — a blocking get here
            # would throttle every active request's TPOT.
            if not self.engine.pending():
                try:
                    self._submit(self._submit_q.get(timeout=0.2))
                except queue.Empty:
                    pass
            while True:
                try:
                    self._submit(self._submit_q.get_nowait())
                except queue.Empty:
                    break
            if not self.engine.pending():
                continue
            try:
                finished = self.engine.step()
            except Exception:
                logger.exception("engine step failed")
                continue
            now = time.perf_counter()
            for request_id, tokens in finished:
                with self._inflight_lock:
                    pending = self._inflight.get(request_id)
                if pending is None:
                    continue
                pending.tokens = tokens
                pending.finished_at = now
                pending.event.set()

    def _submit(self, pending: _Pending) -> None:
        try:
            self.engine.submit(pending.request)
        except ValueError as exc:
            pending.error = str(exc)
            pending.finished_at = time.perf_counter()
            pending.event.set()

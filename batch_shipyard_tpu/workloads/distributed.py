"""Shared bootstrap for distributed workload payloads.

Reads the gang env synthesized by jobs/launcher.py (the mpirun-env
analog) and initializes jax.distributed accordingly; single-instance
runs skip initialization. Every recipe payload calls setup() first.
"""

from __future__ import annotations

import os

import jax


def setup() -> dict:
    """Initialize jax.distributed from the SHIPYARD/JAX env contract;
    returns a context dict with process/topology info."""
    instances = int(os.environ.get("SHIPYARD_TASK_INSTANCES", "1"))
    instance = int(os.environ.get("SHIPYARD_TASK_INSTANCE", "0"))
    if instances > 1 and os.environ.get("JAX_COORDINATOR_ADDRESS"):
        # jax.distributed.initialize reads JAX_COORDINATOR_ADDRESS,
        # JAX_NUM_PROCESSES, JAX_PROCESS_ID from the env our launcher
        # synthesized (batch.py:4362 _construct_mpi_command analog).
        jax.distributed.initialize()
    return {
        "instances": instances,
        "instance": instance,
        "process_index": jax.process_index(),
        "process_count": jax.process_count(),
        "local_devices": jax.local_device_count(),
        "global_devices": jax.device_count(),
    }


def log(ctx: dict, message: str) -> None:
    print(f"[proc {ctx['process_index']}/{ctx['process_count']}] "
          f"{message}", flush=True)

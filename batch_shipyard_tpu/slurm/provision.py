"""Slurm control-plane provisioning: controller/login VMs, slurmdbd +
MariaDB accounting, munge key distribution, power-save wrappers, and
the compute-node join path.

Reference analog: slurm/slurm.py (cluster create),
scripts/shipyard_slurm_master_bootstrap.sh (controller: slurm +
slurmdbd + MySQL + munge key export + generated resume/suspend
wrappers, :637-668), scripts/shipyard_slurm_computenode_nodeprep.sh
(munge key poll + slurmd join), slurm/slurmdb.sql + slurmdbd.conf.

TPU-native redesign: where the reference distributes the munge key
over an Azure file share and drives VMs through ARM, ours publishes
the key through the framework's StateStore object API (the same
storage-mediated channel every other subsystem uses — works with the
localfs store in tests and GCS in production) and provisions VMs with
substrate/gce_vm.GceVmManager. The power-save wrappers call the
framework CLI (`shipyard-tpu slurm resume/suspend`), whose handshake
logic lives in slurm/burst.py.
"""

from __future__ import annotations

import time
from typing import Optional

from batch_shipyard_tpu.state import names
from batch_shipyard_tpu.state.base import NotFoundError, StateStore
from batch_shipyard_tpu.utils import util

logger = util.get_logger(__name__)

_CLUSTERS_PK = "clusters"


def munge_key_object(cluster_id: str) -> str:
    """Store key under which the cluster's munge key is published."""
    return f"slurm/{cluster_id}/munge.key"


def publish_munge_key(store: StateStore, cluster_id: str,
                      key_bytes: bytes) -> None:
    """Controller-side: publish the generated munge key (bootstrap's
    'export munge key to storage' step)."""
    store.put_object(munge_key_object(cluster_id), key_bytes)


def fetch_munge_key(store: StateStore, cluster_id: str,
                    timeout: float = 600.0,
                    poll_interval: float = 2.0) -> bytes:
    """Compute/login-side: poll for the controller's munge key
    (computenode_nodeprep's 'Waiting for munge key' loop)."""
    deadline = time.monotonic() + timeout
    while True:
        try:
            data = store.get_object(munge_key_object(cluster_id))
            if data:
                return data
        except (NotFoundError, KeyError):
            pass
        if time.monotonic() >= deadline:
            raise TimeoutError(
                f"munge key for {cluster_id} not published within "
                f"{timeout}s")
        time.sleep(poll_interval)


def generate_slurmdbd_conf(controller_host: str, db_password: str,
                           log_dir: str = "/var/log/slurm") -> str:
    """slurmdbd.conf for the accounting daemon backed by local
    MariaDB (reference slurm/slurmdbd.conf shape, our text)."""
    return f"""# batch-shipyard-tpu slurmdbd configuration
AuthType=auth/munge
DbdAddr={controller_host}
DbdHost={controller_host}
DbdPort=6819
SlurmUser=slurm
PidFile=/var/run/slurmdbd.pid
LogFile={log_dir}/slurmdbd.log
DebugLevel=4
StorageType=accounting_storage/mysql
StorageUser=slurm
StoragePass={db_password}
StorageLoc=slurm_acct_db
"""


def generate_db_init_sql(db_password: str) -> str:
    """Accounting database bootstrap SQL (reference slurmdb.sql role,
    modern auth syntax)."""
    return f"""CREATE DATABASE IF NOT EXISTS slurm_acct_db;
CREATE USER IF NOT EXISTS 'slurm'@'localhost'
  IDENTIFIED BY '{db_password}';
GRANT ALL PRIVILEGES ON slurm_acct_db.* TO 'slurm'@'localhost';
FLUSH PRIVILEGES;
"""


def generate_power_save_wrappers(configdir: str = "/opt/shipyard/config",
                                 log_dir: str = "/var/log/slurm"
                                 ) -> dict[str, str]:
    """The three generated power-save programs slurm.conf points at
    (reference master_bootstrap.sh:637-668 writes these inline; ours
    are returned for the bootstrap to install under /opt/shipyard).

    Each expands the slurm hostlist with scontrol and hands it to the
    framework CLI, which runs the storage-mediated resume/suspend
    handshake (slurm/burst.py)."""
    def wrapper(verb: str) -> str:
        return f"""#!/usr/bin/env bash
set -uo pipefail
hosts=$(scontrol show hostnames "$1" | paste -sd, -)
python3 -m batch_shipyard_tpu.cli.main --configdir {configdir} \\
  slurm {verb} "$hosts" >> {log_dir}/power-save.log 2>&1
"""
    return {
        "slurm_resume.sh": wrapper("resume"),
        "slurm_suspend.sh": wrapper("suspend"),
        # Resume failure is handled as a suspend (release bindings so
        # slurm can retry elsewhere) — same policy as the reference's
        # ResumeFailProgram wrapper.
        "slurm_resume_fail.sh": wrapper("suspend"),
    }


def _install_files_script(files: dict[str, str], dest: str) -> str:
    """Bash fragment writing each file via quoted heredoc."""
    parts = []
    for filename, content in sorted(files.items()):
        parts.append(
            f"cat > {dest}/{filename} <<'SHIPYARD_EOF'\n"
            f"{content}SHIPYARD_EOF\n"
            f"chmod 755 {dest}/{filename}")
    return "\n".join(parts)


def _framework_install_script(package_source: str,
                              configdir: str,
                              store_config_yaml: Optional[str]) -> str:
    """Bash fragment installing the framework CLI + its store config —
    the munge-key publication and power-save wrappers depend on both.

    package_source: pip requirement or URL (a gs:// wheel is fetched
    with gcloud storage first).
    store_config_yaml: credentials.yaml content pointing the CLI at
    the shared state store (required for any store-mediated step).
    """
    if package_source.startswith("gs://"):
        install = (f"gcloud storage cp {package_source} "
                   f"/tmp/shipyard-pkg.whl\n"
                   f"pip3 install --break-system-packages "
                   f"/tmp/shipyard-pkg.whl\n"
                   f"rm -f /tmp/shipyard-pkg.whl")
    else:
        install = (f"pip3 install --break-system-packages "
                   f"{package_source}")
    config = ""
    if store_config_yaml is not None:
        config = (f"mkdir -p {configdir}\n"
                  f"cat > {configdir}/credentials.yaml "
                  f"<<'SHIPYARD_EOF'\n{store_config_yaml}"
                  f"{'' if store_config_yaml.endswith(chr(10)) else chr(10)}"
                  f"SHIPYARD_EOF\n"
                  f"chmod 600 {configdir}/credentials.yaml")
    return f"{install}\n{config}"


def generate_controller_bootstrap(
        cluster_id: str, slurm_conf: str, db_password: str,
        configdir: str = "/opt/shipyard/config",
        with_slurmdbd: bool = True,
        package_source: str = "batch-shipyard-tpu",
        store_config_yaml: Optional[str] = None) -> str:
    """First-boot script for the slurm controller VM: framework CLI
    install + store config, packages, accounting DB, munge key
    generation + publication through the framework store, power-save
    wrappers, slurm.conf, daemons.
    (reference shipyard_slurm_master_bootstrap.sh role)."""
    wrappers = _install_files_script(
        generate_power_save_wrappers(configdir), "/opt/shipyard")
    framework = _framework_install_script(package_source, configdir,
                                          store_config_yaml)
    dbd = ""
    if with_slurmdbd:
        dbd = f"""
# ---- accounting: mariadb + slurmdbd ----
apt-get install -y mariadb-server slurmdbd
systemctl enable --now mariadb
mysql <<'SHIPYARD_EOF'
{generate_db_init_sql(db_password)}SHIPYARD_EOF
cat > /etc/slurm/slurmdbd.conf <<'SHIPYARD_EOF'
{generate_slurmdbd_conf("localhost", db_password)}SHIPYARD_EOF
chown slurm:slurm /etc/slurm/slurmdbd.conf
chmod 600 /etc/slurm/slurmdbd.conf
systemctl enable --now slurmdbd
"""
    return f"""#!/usr/bin/env bash
set -euo pipefail
# batch-shipyard-tpu slurm controller bootstrap ({cluster_id})
apt-get update
apt-get install -y slurmctld munge python3-pip
mkdir -p /opt/shipyard /var/spool/slurm /var/log/slurm /etc/slurm
chown -R slurm:slurm /var/spool/slurm /var/log/slurm

# ---- framework CLI + store config (munge publication and the
# power-save wrappers both need it) ----
{framework}

# ---- munge key: generate and publish through the framework store ----
systemctl enable --now munge
python3 -m batch_shipyard_tpu.cli.main --configdir {configdir} \\
  slurm publish-munge-key --cluster-id {cluster_id} \\
  --key-file /etc/munge/munge.key
{dbd}
# ---- power-save wrapper programs ----
{wrappers}

# ---- slurm.conf ----
cat > /etc/slurm/slurm.conf <<'SHIPYARD_EOF'
{slurm_conf}SHIPYARD_EOF
systemctl enable --now slurmctld
"""


def generate_compute_join_script(
        cluster_id: str, slurm_conf: str,
        configdir: str = "/opt/shipyard/config",
        package_source: str = "batch-shipyard-tpu",
        store_config_yaml: Optional[str] = None) -> str:
    """Compute-node slurmd join: poll the munge key from the store,
    install, start slurmd (reference
    shipyard_slurm_computenode_nodeprep.sh role)."""
    framework = _framework_install_script(package_source, configdir,
                                          store_config_yaml)
    return f"""#!/usr/bin/env bash
set -euo pipefail
# batch-shipyard-tpu slurm compute-node join ({cluster_id})
apt-get update
apt-get install -y slurmd munge python3-pip
mkdir -p /etc/slurm /var/spool/slurm /var/log/slurm
{framework}
# ---- munge key: poll until the controller publishes it ----
python3 -m batch_shipyard_tpu.cli.main --configdir {configdir} \\
  slurm fetch-munge-key --cluster-id {cluster_id} \\
  --key-file /etc/munge/munge.key
chmod 400 /etc/munge/munge.key
chown munge:munge /etc/munge/munge.key
systemctl enable --now munge
munge -n | unmunge

cat > /etc/slurm/slurm.conf <<'SHIPYARD_EOF'
{slurm_conf}SHIPYARD_EOF
systemctl enable slurmd
for attempt in 1 2 3 4 5; do
  systemctl restart slurmd && break
  sleep 10
done
systemctl --no-pager status slurmd
"""


def generate_login_bootstrap(
        cluster_id: str, slurm_conf: str,
        configdir: str = "/opt/shipyard/config",
        package_source: str = "batch-shipyard-tpu",
        store_config_yaml: Optional[str] = None) -> str:
    """Login-node bootstrap: munge + client tools only."""
    framework = _framework_install_script(package_source, configdir,
                                          store_config_yaml)
    return f"""#!/usr/bin/env bash
set -euo pipefail
# batch-shipyard-tpu slurm login-node bootstrap ({cluster_id})
apt-get update
apt-get install -y slurm-client munge python3-pip
mkdir -p /etc/slurm
{framework}
python3 -m batch_shipyard_tpu.cli.main --configdir {configdir} \\
  slurm fetch-munge-key --cluster-id {cluster_id} \\
  --key-file /etc/munge/munge.key
chmod 400 /etc/munge/munge.key
chown munge:munge /etc/munge/munge.key
systemctl enable --now munge
cat > /etc/slurm/slurm.conf <<'SHIPYARD_EOF'
{slurm_conf}SHIPYARD_EOF
"""


def create_slurm_cluster(store: StateStore, cluster_id: str,
                         slurm_conf: str, db_password: str,
                         project: str, zone: Optional[str] = None,
                         network: Optional[str] = None,
                         controller_vm_size: str = "e2-standard-4",
                         login_vm_size: str = "e2-standard-2",
                         login_count: int = 0,
                         package_source: str = "batch-shipyard-tpu",
                         store_config_yaml: Optional[str] = None,
                         public_ip: bool = True,
                         vms=None) -> dict:
    """Provision the control plane: controller VM (+ optional login
    VMs), record the cluster (reference slurm.py create_slurm_* +
    fleet.action_slurm_cluster_create analog).

    store_config_yaml: credentials.yaml content giving the VMs access
    to the shared state store (munge key channel + power-save
    handshake). ``vms`` injects a GceVmManager for tests."""
    if vms is None:
        from batch_shipyard_tpu.substrate.gce_vm import GceVmManager
        vms = GceVmManager(project, zone=zone, network=network)
    controller_name = f"shipyard-slurm-{cluster_id}-controller"
    controller_ip = vms.create_vm(
        controller_name, controller_vm_size, public_ip=public_ip,
        startup_script=generate_controller_bootstrap(
            cluster_id, slurm_conf, db_password,
            package_source=package_source,
            store_config_yaml=store_config_yaml),
        tags=("shipyard-slurm", "slurm-controller"))
    logins = {}
    for i in range(login_count):
        name = f"shipyard-slurm-{cluster_id}-login{i}"
        logins[name] = vms.create_vm(
            name, login_vm_size, public_ip=public_ip,
            startup_script=generate_login_bootstrap(
                cluster_id, slurm_conf,
                package_source=package_source,
                store_config_yaml=store_config_yaml),
            tags=("shipyard-slurm", "slurm-login"))
    record = {
        "controller": controller_name,
        "controller_ip": controller_ip,
        "logins": logins,
        "state": "provisioned",
        "created_at": util.datetime_utcnow_iso(),
    }
    store.upsert_entity(names.TABLE_SLURM, _CLUSTERS_PK, cluster_id,
                        record)
    return record


def destroy_slurm_cluster(store: StateStore, cluster_id: str,
                          project: str, zone: Optional[str] = None,
                          vms=None) -> None:
    """Tear down the control plane VMs and the cluster record."""
    if vms is None:
        from batch_shipyard_tpu.substrate.gce_vm import GceVmManager
        vms = GceVmManager(project, zone=zone)
    try:
        record = store.get_entity(names.TABLE_SLURM, _CLUSTERS_PK,
                                  cluster_id)
    except NotFoundError:
        raise ValueError(f"slurm cluster {cluster_id} not found")
    vms.delete_vm(record["controller"])
    for name in record.get("logins", {}):
        vms.delete_vm(name)
    store.delete_entity(names.TABLE_SLURM, _CLUSTERS_PK, cluster_id)


def slurm_cluster_status(store: StateStore, cluster_id: str,
                         project: Optional[str] = None,
                         zone: Optional[str] = None,
                         vms=None) -> dict:
    try:
        record = store.get_entity(names.TABLE_SLURM, _CLUSTERS_PK,
                                  cluster_id)
    except NotFoundError:
        raise ValueError(f"slurm cluster {cluster_id} not found")
    status = {"cluster": record}
    if project or vms is not None:
        if vms is None:
            from batch_shipyard_tpu.substrate.gce_vm import GceVmManager
            vms = GceVmManager(project, zone=zone)
        try:
            status["controller_status"] = vms.vm_status(
                record["controller"])
        except Exception as exc:  # noqa: BLE001 - live probe optional
            status["controller_status"] = f"unknown ({exc})"
    return status


def _cluster_record(store: StateStore, cluster_id: str) -> dict:
    try:
        return store.get_entity(names.TABLE_SLURM, _CLUSTERS_PK,
                                cluster_id)
    except NotFoundError:
        raise ValueError(f"slurm cluster {cluster_id} not found")


def suspend_slurm_cluster(store: StateStore, cluster_id: str,
                          project: Optional[str] = None,
                          zone: Optional[str] = None,
                          vms=None) -> list[str]:
    """Stop the control-plane VMs in place (reference `slurm cluster
    suspend`, shipyard.py:2918+): controller + every login VM.
    Compute nodes are pool slices — `pool suspend` owns those."""
    from batch_shipyard_tpu.utils import service_vm
    vms = service_vm.default_vms(project, zone, vms)
    record = _cluster_record(store, cluster_id)
    stopped = []
    for name in [record["controller"], *record.get("logins", {})]:
        service_vm.suspend_vm(vms, name)
        stopped.append(name)
    store.merge_entity(names.TABLE_SLURM, _CLUSTERS_PK, cluster_id,
                       {"state": "suspended"})
    return stopped


def start_slurm_cluster(store: StateStore, cluster_id: str,
                        project: Optional[str] = None,
                        zone: Optional[str] = None,
                        vms=None) -> list[str]:
    """Restart suspended control-plane VMs (reference `slurm cluster
    start`)."""
    from batch_shipyard_tpu.utils import service_vm
    vms = service_vm.default_vms(project, zone, vms)
    record = _cluster_record(store, cluster_id)
    started = []
    for name in [record["controller"], *record.get("logins", {})]:
        service_vm.start_vm(vms, name)
        started.append(name)
    store.merge_entity(names.TABLE_SLURM, _CLUSTERS_PK, cluster_id,
                       {"state": "provisioned"})
    return started


def slurm_ssh_argv(store: StateStore, cluster_id: str,
                   target: str = "controller", index: int = 0,
                   partition: Optional[str] = None,
                   host: Optional[str] = None,
                   username: Optional[str] = None,
                   ssh_private_key: Optional[str] = None,
                   command: Optional[str] = None) -> list[str]:
    """ssh argv into the cluster (reference `slurm ssh controller|
    login|node`, shipyard.py:2918+). target='node' resolves a slurm
    compute host to its pool node ip via the burst daemon's
    assignment rows (host= the slurm hostname, partition= its
    partition)."""
    from batch_shipyard_tpu.utils import service_vm
    record = _cluster_record(store, cluster_id)
    if target == "controller":
        ip = record.get("controller_ip")
        if not ip:
            raise ValueError(f"cluster {cluster_id} has no "
                             f"controller ip recorded")
    elif target == "login":
        logins = sorted(record.get("logins", {}).items())
        if index >= len(logins):
            raise ValueError(
                f"cluster {cluster_id} has {len(logins)} login "
                f"VM(s); no index {index}")
        ip = logins[index][1]
    elif target == "node":
        if not (partition and host):
            raise ValueError(
                "slurm ssh node requires partition and host")
        pk = f"{cluster_id}${partition}"
        try:
            row = store.get_entity(names.TABLE_SLURM, pk, host)
        except NotFoundError:
            raise ValueError(
                f"slurm host {host} has no pool node assigned "
                f"(partition {partition})")
        ip = row.get("internal_ip")
        if not ip:
            raise ValueError(f"slurm host {host} has no recorded ip")
    else:
        raise ValueError(
            f"unknown ssh target {target!r} "
            f"(controller|login|node)")
    return service_vm.ssh_argv(ip, username, ssh_private_key,
                               command)

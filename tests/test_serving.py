"""Continuous batching engine: greedy equivalence with the lockstep
generator, slot reuse, early-eos, and per-slot cache isolation."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from batch_shipyard_tpu.models import inference as inf
from batch_shipyard_tpu.models import serving
from batch_shipyard_tpu.models import transformer as tfm

CFG = tfm.TransformerConfig(
    vocab_size=97, d_model=32, n_layers=2, n_heads=2, d_head=16,
    d_ff=64, max_seq_len=64, dtype=jnp.float32,
    param_dtype=jnp.float32)


@pytest.fixture(scope="module")
def params():
    model = tfm.TransformerLM(CFG)
    tokens = jnp.zeros((1, 8), jnp.int32)
    return model.init(jax.random.PRNGKey(7), tokens)["params"]


def reference_greedy(params, prompt, num_tokens):
    run, _model = inf.make_decoder(CFG, params, max_decode_len=64)
    tokens, _cache = run(jnp.asarray([prompt], jnp.int32), num_tokens,
                         jax.random.PRNGKey(0))
    return list(np.asarray(tokens[0, len(prompt):]))


def test_continuous_batching_matches_lockstep(params):
    """5 requests with different prompt lengths through a 2-slot
    engine produce EXACTLY the tokens batch-1 greedy decoding
    produces for each — slots at different depths don't interfere."""
    rng = np.random.RandomState(0)
    requests = [
        serving.Request(f"r{i}", list(rng.randint(0, 97, (3 + i,))),
                        max_new_tokens=4 + (i % 3))
        for i in range(5)
    ]
    engine = serving.ContinuousBatcher(CFG, params, num_slots=2,
                                       max_decode_len=64)
    for req in requests:
        engine.submit(req)
    results = {}
    for _ in range(200):
        for rid, toks in engine.step():
            results[rid] = toks
        if not engine.pending():
            break
    assert set(results) == {r.request_id for r in requests}
    for req in requests:
        want = reference_greedy(params, req.prompt, req.max_new_tokens)
        assert results[req.request_id] == want, (
            req.request_id, results[req.request_id], want)


def test_eos_frees_slot_early(params):
    """A request whose first sampled token is its eos finishes in one
    step and its slot is immediately reused."""
    rng = np.random.RandomState(1)
    prompt = list(rng.randint(0, 97, (4,)))
    first = reference_greedy(params, prompt, 1)[0]
    engine = serving.ContinuousBatcher(CFG, params, num_slots=1,
                                       max_decode_len=64)
    engine.submit(serving.Request("eos", prompt, max_new_tokens=10,
                                  eos_id=first))
    other = list(rng.randint(0, 97, (5,)))
    engine.submit(serving.Request("next", other, max_new_tokens=3))
    results = {}
    for _ in range(50):
        for rid, toks in engine.step():
            results[rid] = toks
        if not engine.pending():
            break
    assert results["eos"] == [first]
    assert results["next"] == reference_greedy(params, other, 3)


def test_submit_rejects_overflow(params):
    engine = serving.ContinuousBatcher(CFG, params, num_slots=1,
                                       max_decode_len=16)
    with pytest.raises(ValueError, match="exceeds max_decode_len"):
        engine.submit(serving.Request("big", [1] * 10,
                                      max_new_tokens=10))


def test_paged_engine_matches_dense(params):
    """The paged KV cache (block tables over a shared page pool)
    produces exactly the dense engine's greedy outputs, including
    prompts that are exact page multiples and generations that cross
    page boundaries."""
    rng = np.random.RandomState(2)
    requests = [
        serving.Request("p0", list(rng.randint(0, 97, (8,))),  # =page
                        max_new_tokens=9),                     # cross
        serving.Request("p1", list(rng.randint(0, 97, (3,))),
                        max_new_tokens=6),
        serving.Request("p2", list(rng.randint(0, 97, (13,))),
                        max_new_tokens=4),
    ]
    engine = serving.ContinuousBatcher(
        CFG, params, num_slots=2, max_decode_len=64, kv_page_size=8)
    for r in requests:
        engine.submit(serving.Request(r.request_id, r.prompt,
                                      r.max_new_tokens))
    results = {}
    for _ in range(200):
        for rid, toks in engine.step():
            results[rid] = toks
        if not engine.pending():
            break
    for r in requests:
        want = reference_greedy(params, r.prompt, r.max_new_tokens)
        assert results[r.request_id] == want, (r.request_id,
                                               results[r.request_id],
                                               want)


def test_paged_pool_overcommit_admission_waits(params):
    """With a page pool smaller than slots*max_len, admission waits
    for frees instead of deadlocking; pages are recycled across
    requests and everything completes."""
    rng = np.random.RandomState(3)
    reqs = [serving.Request(f"o{i}", list(rng.randint(0, 97, (8,))),
                            max_new_tokens=6) for i in range(4)]
    # 3 pages of 8 = 24 tokens total: one request (8+6 tokens -> 2
    # pages) fits; two concurrent would need 4.
    engine = serving.ContinuousBatcher(
        CFG, params, num_slots=2, max_decode_len=32, kv_page_size=8,
        kv_num_pages=3)
    for r in reqs:
        engine.submit(r)
    results = {}
    for _ in range(400):
        for rid, toks in engine.step():
            results[rid] = toks
        if not engine.pending():
            break
    assert set(results) == {r.request_id for r in reqs}
    for r in reqs:
        assert results[r.request_id] == reference_greedy(
            params, r.prompt, r.max_new_tokens)
    # All pages reclaimable after drain: free, or parked unreferenced
    # in the prefix-cache LRU (indexed for reuse, evictable on
    # demand) — none pinned.
    assert len(engine._free_pages) + len(engine._lru) == 3
    assert all(ref == 0 for ref in engine._page_ref.values())


def test_paged_freed_slot_cannot_corrupt_recycled_pages(params):
    """Regression: a freed slot keeps decoding (masked) in the full
    batch; its stale block table must not scribble over pages that
    were returned to the pool and reallocated to a still-active slot.
    r0 finishes early mid-page; r1 keeps generating across page
    boundaries using recycled pages; r1's output must stay exactly
    equal to the reference."""
    rng = np.random.RandomState(4)
    p0 = list(rng.randint(0, 97, (5,)))
    p1 = list(rng.randint(0, 97, (6,)))
    engine = serving.ContinuousBatcher(
        CFG, params, num_slots=2, max_decode_len=48, kv_page_size=8,
        kv_num_pages=6)
    engine.submit(serving.Request("r0", p0, max_new_tokens=2))
    engine.submit(serving.Request("r1", p1, max_new_tokens=30))
    results = {}
    for _ in range(100):
        for rid, toks in engine.step():
            results[rid] = toks
        if not engine.pending():
            break
    assert results["r0"] == reference_greedy(params, p0, 2)
    assert results["r1"] == reference_greedy(params, p1, 30)


def test_paged_submit_rejects_unadmittable(params):
    engine = serving.ContinuousBatcher(
        CFG, params, num_slots=2, max_decode_len=32, kv_page_size=8,
        kv_num_pages=3)
    with pytest.raises(ValueError, match="could never admit"):
        engine.submit(serving.Request("huge", [1] * 20,
                                      max_new_tokens=12))


def test_prefill_buckets_bound_compiles():
    """Prompts of different lengths inside one power-of-two bucket
    share a single prefill compilation; a longer prompt crossing into
    the next bucket adds exactly one more. The prefill jit is
    module-level (same-config engines share compiles), so measure
    CACHE-SIZE DELTAS with a config unique to this test."""
    ucfg = tfm.TransformerConfig(
        vocab_size=101, d_model=32, n_layers=2, n_heads=2, d_head=16,
        d_ff=64, max_seq_len=64, dtype=jnp.float32,
        param_dtype=jnp.float32)
    uparams = tfm.TransformerLM(ucfg).init(
        jax.random.PRNGKey(5), jnp.zeros((1, 8), jnp.int32))["params"]
    eng = serving.ContinuousBatcher(ucfg, uparams, num_slots=4,
                                    max_decode_len=64)
    base = serving._prefill_dense._cache_size()
    for rid, n in (("a", 3), ("b", 5), ("c", 11)):   # bucket 16
        eng.submit(serving.Request(rid, [7] * n, max_new_tokens=2))
    done = []
    for _ in range(30):
        done += eng.step()
        if len(done) == 3:
            break
    assert len(done) == 3
    assert serving._prefill_dense._cache_size() == base + 1
    eng.submit(serving.Request("d", [7] * 20, max_new_tokens=2))
    for _ in range(30):
        done += eng.step()
        if len(done) == 4:
            break
    assert len(done) == 4
    assert serving._prefill_dense._cache_size() == base + 2


def test_paged_prefill_bucket_shorter_than_page(params):
    """A prompt whose bucket is smaller than the page size still
    writes its (single, partial) page correctly: greedy output equals
    the dense engine's."""
    prompt = [5, 9, 2]                     # bucket 16 < page 32
    dense = serving.ContinuousBatcher(CFG, params, num_slots=2,
                                      max_decode_len=64)
    paged = serving.ContinuousBatcher(CFG, params, num_slots=2,
                                      max_decode_len=64,
                                      kv_page_size=32)
    outs = []
    for eng in (dense, paged):
        eng.submit(serving.Request("r", prompt, max_new_tokens=6))
        got = []
        for _ in range(20):
            got += eng.step()
            if got:
                break
        outs.append(got[0][1])
    assert outs[0] == outs[1], outs


def test_int8_quantized_serving_generates(params):
    """ROADMAP 'int8 serving via QuantDense': a quantize_matmuls
    config runs the whole continuous-batching path on the int8
    kernels (interpret mode here; MXU int8 on hardware)."""
    from jax.experimental.pallas import tpu as pltpu
    qcfg = tfm.TransformerConfig(
        vocab_size=97, d_model=128, n_layers=1, n_heads=2, d_head=64,
        d_ff=128, dtype=jnp.float32, param_dtype=jnp.float32,
        quantize_matmuls=True)
    with pltpu.force_tpu_interpret_mode():
        qparams = tfm.TransformerLM(qcfg).init(
            jax.random.PRNGKey(1),
            jnp.zeros((1, 8), jnp.int32))["params"]
        eng = serving.ContinuousBatcher(qcfg, qparams, num_slots=2,
                                        max_decode_len=32)
        eng.submit(serving.Request("q", [5, 9], max_new_tokens=3))
        done = []
        for _ in range(10):
            done += eng.step()
            if done:
                break
    assert done and len(done[0][1]) == 3


def test_overcommit_preemption_matches_greedy(params):
    """Force preemptions (pool far below aggregate worst case, long
    generations, no eos): victims are evicted mid-decode, re-queued,
    and resumed via re-prefill of prompt+generated — final outputs
    must STILL match uninterrupted batch-1 greedy decoding exactly."""
    rng = np.random.RandomState(5)
    reqs = [serving.Request(f"p{i}", list(rng.randint(0, 97, (6,))),
                            max_new_tokens=18) for i in range(4)]
    # Worst case per request: ceil((6+18)/8) = 3 pages; aggregate 12.
    # 5 pages forces decode-time exhaustion while both slots run.
    engine = serving.ContinuousBatcher(
        CFG, params, num_slots=2, max_decode_len=32, kv_page_size=8,
        kv_num_pages=5, overcommit=True)
    for r in reqs:
        engine.submit(r)
    results = {}
    for _ in range(600):
        for rid, toks in engine.step():
            results[rid] = toks
        if not engine.pending():
            break
    assert set(results) == {r.request_id for r in reqs}
    assert engine.preemptions > 0, \
        "scenario failed to exercise preemption"
    for r in reqs:
        assert results[r.request_id] == reference_greedy(
            params, r.prompt, r.max_new_tokens), r.request_id
    assert len(engine._free_pages) + len(engine._lru) == 5
    assert all(ref == 0 for ref in engine._page_ref.values())


def test_overcommit_beats_reservation_when_generations_are_short():
    """The overcommit win: requests DECLARE worst-case max_new_tokens
    but actually finish after a couple of tokens (eos). Reservation
    admission serializes them (each reserves the whole pool);
    overcommit runs them concurrently — strictly fewer engine steps,
    identical outputs, zero preemptions needed."""
    model = tfm.TransformerLM(CFG)
    params = model.init(jax.random.PRNGKey(7),
                        jnp.zeros((1, 8), jnp.int32))["params"]
    rng = np.random.RandomState(9)
    prompts = [list(rng.randint(0, 97, (4,))) for _ in range(4)]
    # Discover each prompt's 2nd greedy token and use it as that
    # request's eos: every request really finishes after 2 tokens.
    eos = {i: reference_greedy(params, p, 2)[-1]
           for i, p in enumerate(prompts)}

    def run(overcommit):
        engine = serving.ContinuousBatcher(
            CFG, params, num_slots=4, max_decode_len=32,
            kv_page_size=8, kv_num_pages=4, overcommit=overcommit)
        for i, p in enumerate(prompts):
            engine.submit(serving.Request(
                f"s{i}", p, max_new_tokens=24, eos_id=eos[i]))
        results, steps = {}, 0
        for _ in range(400):
            steps += 1
            for rid, toks in engine.step():
                results[rid] = toks
            if not engine.pending():
                break
        return results, steps, engine.preemptions

    res_r, steps_r, _ = run(overcommit=False)
    res_o, steps_o, preempts = run(overcommit=True)
    assert res_r == res_o
    assert set(res_o) == {f"s{i}" for i in range(4)}
    # Each request: prompt 4 + worst 24 = 28 tokens = 4 pages — the
    # whole pool, so reservation admits ONE at a time (4 sequential
    # waves); overcommit admits all four at once.
    assert steps_o < steps_r, (steps_o, steps_r)
    assert preempts == 0


def test_admission_priority_orders_the_wait_line(params):
    """Queued requests admit in priority order (FIFO within a class);
    active slots are never preempted for priority."""
    engine = serving.ContinuousBatcher(CFG, params, num_slots=1,
                                       max_decode_len=64)
    engine.submit(serving.Request("first", [1, 2],
                                  max_new_tokens=8))
    engine.step()  # 'first' occupies the single slot
    engine.submit(serving.Request("low-a", [3], max_new_tokens=1))
    engine.submit(serving.Request("low-b", [4], max_new_tokens=1))
    engine.submit(serving.Request("hi", [5], max_new_tokens=1,
                                  priority=9))
    order = []
    for _ in range(40):
        for request_id, _tokens in engine.step():
            order.append(request_id)
        if len(order) == 4:
            break
    assert order[0] == "first"          # never preempted
    assert order[1] == "hi"             # overtakes the queue
    assert order[2:] == ["low-a", "low-b"]  # FIFO within class


def test_preempted_victim_resumes_within_its_priority_class(params):
    """A preempted low-priority request resumes ahead of its peers
    but never ahead of a queued HIGHER-priority request."""
    engine = serving.ContinuousBatcher(
        CFG, params, num_slots=2, max_decode_len=64,
        kv_page_size=4, kv_num_pages=4, overcommit=True)
    engine.submit(serving.Request("low1", [1, 2],
                                  max_new_tokens=12))
    engine.submit(serving.Request("low2", [3, 4],
                                  max_new_tokens=12))
    engine.step()  # both lows admit and start decoding
    engine.submit(serving.Request("hi", [5], max_new_tokens=1,
                                  priority=5))
    order = []
    for _ in range(200):
        for request_id, _tokens in engine.step():
            order.append(request_id)
        if len(order) == 3:
            break
    assert engine.preemptions >= 1, order  # page pressure DID preempt
    # The high-priority request admitted into the freed capacity
    # before the preempted low resumed.
    assert order[0] == "hi", (order, engine.preemptions)
    assert set(order[1:]) == {"low1", "low2"}


def test_chunked_prefill_greedy_equivalent(params):
    """prefill_chunk: chunked multi-token inserts with global RoPE
    positions must produce tokens identical to the one-pass prefill
    (dense and paged engines), while bounding prefill memory."""
    prompt = [5, 17, 31, 2, 9, 40, 11, 3, 8, 22, 7, 19, 28, 33,
              41, 6, 13, 2, 55, 60, 61, 44]  # 22 tokens -> 32 bucket

    def run(prefill_chunk, page=None):
        engine = serving.ContinuousBatcher(
            CFG, params, num_slots=2, max_decode_len=64,
            kv_page_size=page, prefill_chunk=prefill_chunk)
        engine.submit(serving.Request("r", list(prompt),
                                      max_new_tokens=10))
        out = None
        while engine.pending():
            for _rid, tokens in engine.step():
                out = tokens
        return out

    for page in (None, 16):
        ref = run(None, page)
        for chunk in (8, 16):
            got = run(chunk, page)
            assert got == ref, (page, chunk, got, ref)

from batch_shipyard_tpu.utils import util  # noqa: F401

"""shipyard lint: the distributed-invariant static analyzer.

Importing this package registers every rule module; see core.py for
the framework and docs/34-static-analysis.md for the rule inventory,
baseline/suppression workflow, and how to author a rule.
"""

from batch_shipyard_tpu.analysis.core import (  # noqa: F401
    BASELINE_FILENAME, AnalysisContext, Finding, Report, RULES,
    analyze, load_baseline, repo_root, run_rules, write_baseline)

# Rule modules register themselves on import (the @rule decorator).
from batch_shipyard_tpu.analysis import (  # noqa: F401,E402
    rules_env, rules_jax, rules_loops, rules_registry, rules_serving,
    rules_shell, rules_sim, rules_store, rules_wiring)

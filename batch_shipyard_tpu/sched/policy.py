"""Pure scheduling policies: goodput-as-controller decision functions.

The goodput ledger (goodput/accounting.py) prices every second of
fleet time into productive / badput legs — but a meter alone changes
nothing. This module closes the loop: placement, victim selection,
and autoscale decisions are expressed as PURE functions over plain
values, each returning (or minimizing) an *estimated badput cost in
seconds*, so every decision is directly comparable against the
ledger that later prices it.

Shared by construction: the live paths (agent/node_agent.py claim +
preemption sweep, pool/autoscale.py) and the discrete-event fleet
simulator (sim/simulator.py) import THESE functions — never copies —
so a simulated policy delta is evidence about production decision
code (asserted by tests/test_fleet_sim.py).

Decisions:

* ``claim_score``     — expected badput seconds of claiming a task on
                        a given node: a cold compile-cache claim pays
                        the cold-compile leg, an unhealthy node pays
                        an expected-failure debit, a node with recent
                        claim failures pays a backoff debit.
* ``should_defer_claim`` — warm-cache affinity window: a cold/risky
                        node hands a *young* task back to the queue so
                        a warm node can claim it; past the window any
                        node claims (affinity must never starve work).
* ``victim_cost``     — expected badput seconds of preempting a
                        running task: replay rework since the last
                        COMMITTED checkpoint plus the warm compile
                        state destroyed, scaled by gang width.
* ``autoscale_target``— explicit provisioning-badput vs
                        queueing-badput trade: add nodes only while a
                        node's provisioning cost buys back more
                        expected queueing seconds than it spends.

Every knob lives in ``PolicyKnobs`` and is declared in pool settings
(config/settings.py ``sched_policy``) + the pool schema — enforced by
tests/test_names_consistency.py.
"""

from __future__ import annotations

import dataclasses
from typing import Optional


@dataclasses.dataclass(frozen=True)
class PolicyKnobs:
    """Tunable constants for every policy decision, all in seconds
    (costs) so decisions compose by addition. Defaults are
    production-shaped; drills and sim scenarios override via pool
    settings (``sched_policy:``)."""

    # --- claim scoring (placement) ---
    # Cold-compile seconds a warm compile-cache claim avoids: the
    # debit a cold node pays when the task names a cache identity.
    warm_cache_bonus_seconds: float = 30.0
    # Expected-failure debit at health 0.0 (scaled linearly by
    # 1 - health): claiming on a flaky node risks a retry round trip.
    health_debit_seconds: float = 120.0
    # Debit per recent claim failure on the node (backoff badput the
    # next failure would add), capped at 4 failures.
    backoff_debit_seconds: float = 30.0
    # Affinity window: a cold/risky node defers a task younger than
    # this (queue age) back to the queue; past it, anyone claims.
    # Sized to the cold-compile cost it can save: waiting up to C
    # seconds of queueing to avoid C seconds of compile badput is
    # the break-even frontier, and a warm slot usually frees well
    # inside it.
    claim_affinity_wait_seconds: float = 30.0

    # --- victim selection (preemption / eviction) ---
    # Warm compile state destroyed by evicting a warm-cache victim
    # (it recompiles on resume).
    victim_warm_cost_seconds: float = 30.0
    # Weight on replay rework (steps past the last COMMITTED
    # checkpoint x step seconds) — 1.0 means rework is priced at
    # wall value.
    victim_step_cost_weight: float = 1.0

    # --- autoscale (provisioning vs queueing badput) ---
    # Provisioning badput one added node pays before it serves.
    provision_seconds_per_node: float = 120.0
    # Mean task service seconds assumed when sizing the backlog
    # drain (live autoscale has no per-task duration oracle).
    avg_task_seconds: float = 60.0
    # Pending wait considered acceptable before scaling up at all.
    queue_tolerance_seconds: float = 30.0


# Ready-made policy bundles: which decisions are active. ``baseline``
# reproduces the pre-policy scheduler (scan-order placement,
# priority-then-task-id victims, reactive autoscale) so every sim
# comparison has an honest control.
@dataclasses.dataclass(frozen=True)
class PolicyConfig:
    name: str
    claim_scoring: bool = False
    victim_by_cost: bool = False
    autoscale_goodput: bool = False


POLICIES: dict = {
    "baseline": PolicyConfig("baseline"),
    "affinity": PolicyConfig("affinity", claim_scoring=True),
    "victim_cost": PolicyConfig("victim_cost", victim_by_cost=True),
    "autoscale": PolicyConfig("autoscale", autoscale_goodput=True),
    "combined": PolicyConfig("combined", claim_scoring=True,
                             victim_by_cost=True,
                             autoscale_goodput=True),
}


def claim_score(*, warm: bool, health: float = 1.0,
                recent_failures: int = 0,
                has_identity: bool = True,
                knobs: Optional[PolicyKnobs] = None) -> float:
    """Expected badput seconds of claiming a task on this node
    (lower is better; 0.0 is a perfect claim).

    ``warm``            — node holds a warm compile cache for the
                          task's identity digest.
    ``health``          — node health in [0, 1] (agent-tracked).
    ``recent_failures`` — node's recent claim-failure count.
    ``has_identity``    — task advertises a compile-cache identity at
                          all; without one there is no cold-compile
                          leg to price (health/backoff still count).
    """
    knobs = knobs or PolicyKnobs()
    score = 0.0
    if has_identity and not warm:
        score += knobs.warm_cache_bonus_seconds
    health = min(1.0, max(0.0, health))
    score += (1.0 - health) * knobs.health_debit_seconds
    score += min(int(recent_failures), 4) * knobs.backoff_debit_seconds
    return score


def should_defer_claim(score: float, queued_seconds: float,
                       knobs: Optional[PolicyKnobs] = None) -> bool:
    """Warm-cache affinity window: hand the task back to the queue
    when this claim would pay a material cost AND the task is young
    enough that a cheaper node plausibly exists. Past the window the
    claim always proceeds — affinity may trade seconds of queueing
    for a cold compile, never starvation."""
    knobs = knobs or PolicyKnobs()
    if queued_seconds >= knobs.claim_affinity_wait_seconds:
        return False
    return score > 0.5 * knobs.warm_cache_bonus_seconds


def victim_cost(*, warm: bool, steps_since_commit: float,
                step_seconds: float, gang_size: int = 1,
                knobs: Optional[PolicyKnobs] = None) -> float:
    """Expected badput seconds of preempting this running task:
    replay rework (steps executed past the last COMMITTED checkpoint
    are re-run on resume, priced at wall value by the accounting
    engine) plus the warm compile state destroyed, scaled by gang
    width (every instance replays)."""
    knobs = knobs or PolicyKnobs()
    rework = max(0.0, float(steps_since_commit)) * \
        max(0.0, float(step_seconds))
    cost = knobs.victim_step_cost_weight * rework
    if warm:
        cost += knobs.victim_warm_cost_seconds
    return cost * max(1, int(gang_size))


def victim_cost_from_row(row: dict,
                         knobs: Optional[PolicyKnobs] = None,
                         ) -> float:
    """Victim cost for a live task entity: reads the sched-hints
    column the agent syncs from the workload's hints file
    (agent/progress.py ``record_sched_hints``). A task that never
    published hints prices at 0.0 — nothing committed, nothing warm,
    nothing to replay that we know of — and falls back to the
    deterministic (priority, cost, task_id) tie-break."""
    from batch_shipyard_tpu.state import names
    hints = row.get(names.TASK_COL_SCHED_HINTS)
    if not isinstance(hints, dict):
        return 0.0
    spec = row.get("spec") or {}
    gang = int((spec.get("multi_instance") or {})
               .get("num_instances", 1) or 1)
    step = float(hints.get("step", 0) or 0)
    ckpt = float(hints.get("ckpt_step", 0) or 0)
    return victim_cost(
        warm=bool(hints.get("cache_identity")),
        steps_since_commit=step - ckpt,
        step_seconds=float(hints.get("step_seconds", 0.0) or 0.0),
        gang_size=gang, knobs=knobs)


def victim_sort_key(priority: int, cost: float, task_id: str) -> tuple:
    """THE deterministic victim order, shared by the live sweep, the
    drill, and the sim: lowest priority first, then cheapest goodput
    cost, then task id — never scan order, so assertions on the
    elected victim cannot flake on dict ordering."""
    return (int(priority), float(cost), str(task_id))


def autoscale_target(*, pending_tasks: int, active_tasks: int,
                     current_nodes: int, slots_per_node: int,
                     knobs: Optional[PolicyKnobs] = None,
                     ) -> tuple[int, str]:
    """Target node count that explicitly trades provisioning badput
    against queueing badput; returns (target, reason).

    Model: the pending backlog is ``pending * avg_task_seconds`` of
    work; with n serving nodes it drains in ``backlog / (n*slots)``
    and each pending task waits half the horizon on average, so the
    expected queueing badput with n nodes is
    ``pending * horizon(n) / 2``. Starting from the busy-node floor,
    nodes are added while one more node saves more expected queueing
    seconds than the ``provision_seconds_per_node`` it costs — the
    marginal-value stopping rule. With an empty queue the fleet
    shrinks to the busy floor (idle badput has no offsetting
    queueing saving)."""
    knobs = knobs or PolicyKnobs()
    slots = max(1, int(slots_per_node))
    busy = -(-max(0, int(active_tasks)) // slots)  # ceil division
    pending = max(0, int(pending_tasks))
    if pending == 0:
        # Drain TOWARD the busy floor, at most 10% of the fleet per
        # call: a retired node costs a full provisioning round trip
        # to get back, so an empty queue on one tick is weak evidence
        # the capacity is surplus. Damping turns trough scale-down
        # into a ramp instead of a cliff and kills the
        # shrink/re-provision churn a reactive target exhibits.
        step = max(1, current_nodes // 10)
        target = max(busy, current_nodes - step)
        if target < current_nodes:
            return target, (f"drain toward busy floor {busy}: no "
                            f"queue, idle badput unpaid-for")
        return max(target, current_nodes), "steady: no pending work"
    backlog = pending * knobs.avg_task_seconds

    def queueing(n: int) -> float:
        horizon = backlog / (max(1, n) * slots)
        return pending * horizon / 2.0

    n = max(busy, 1)
    if queueing(max(n, current_nodes)) <= \
            pending * knobs.queue_tolerance_seconds / 2.0:
        # Backlog drains inside tolerance with what we have.
        return max(n, current_nodes), "queue within tolerance"
    while queueing(n) - queueing(n + 1) > \
            knobs.provision_seconds_per_node:
        n += 1
    saved = queueing(max(busy, 1)) - queueing(n)
    paid = (n - max(busy, 1)) * knobs.provision_seconds_per_node
    return max(n, busy), (
        f"marginal trade: +{n - max(busy, 1)} node(s) pay "
        f"{paid:.0f}s provisioning to save {saved:.0f}s queueing")


def knobs_from_settings(sched_policy) -> PolicyKnobs:
    """PolicyKnobs from a pool's ``SchedPolicySettings`` (or None →
    defaults); kept here so every consumer derives knobs the same
    way."""
    if sched_policy is None:
        return PolicyKnobs()
    fields = {f.name for f in dataclasses.fields(PolicyKnobs)}
    values = {name: getattr(sched_policy, name)
              for name in fields
              if getattr(sched_policy, name, None) is not None}
    return PolicyKnobs(**values)

"""KV-cache decode correctness: cached single-step decoding must
reproduce the full-forward teacher-forced argmax path exactly."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from batch_shipyard_tpu.models import inference, transformer as tfm


@pytest.fixture(scope="module")
def setup():
    config = tfm.TransformerConfig(
        vocab_size=97, d_model=64, n_layers=2, n_heads=4, d_head=16,
        d_ff=128, max_seq_len=64, dtype=jnp.float32,
        param_dtype=jnp.float32)
    model = tfm.TransformerLM(config)
    tokens = jnp.zeros((2, 8), jnp.int32)
    params = model.init(jax.random.PRNGKey(0), tokens)["params"]
    return config, model, params


def test_greedy_decode_matches_full_forward(setup):
    config, model, params = setup
    rng = np.random.RandomState(0)
    prompt = jnp.asarray(rng.randint(0, 97, (2, 6)), jnp.int32)
    run, _ = inference.make_decoder(config, params, max_decode_len=32)
    out, _cache = run(prompt, 10, jax.random.PRNGKey(1))
    assert out.shape == (2, 16)
    np.testing.assert_array_equal(np.asarray(out[:, :6]),
                                  np.asarray(prompt))
    # Reference: greedy rollout via repeated full forwards (no cache).
    seq = prompt
    for _ in range(10):
        logits = model.apply({"params": params}, seq)
        nxt = jnp.argmax(logits[:, -1].astype(jnp.float32),
                         axis=-1).astype(jnp.int32)
        seq = jnp.concatenate([seq, nxt[:, None]], axis=1)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(seq))


def test_sampling_temperature_and_topk(setup):
    config, model, params = setup
    prompt = jnp.asarray([[1, 2, 3]], jnp.int32)
    run, _ = inference.make_decoder(config, params, max_decode_len=32)
    sampling = inference.SamplingConfig(temperature=1.0, top_k=5)
    out_a, _ = run(prompt, 8, jax.random.PRNGKey(7),
                   sampling=sampling)
    out_b, _ = run(prompt, 8, jax.random.PRNGKey(8),
                   sampling=sampling)
    assert out_a.shape == (1, 11)
    # Different keys should (overwhelmingly) give different samples.
    assert not np.array_equal(np.asarray(out_a), np.asarray(out_b))
    # Same key reproduces exactly.
    out_c, _ = run(prompt, 8, jax.random.PRNGKey(7),
                   sampling=sampling)
    np.testing.assert_array_equal(np.asarray(out_a),
                                  np.asarray(out_c))


def test_decode_respects_max_len(setup):
    config, model, params = setup
    run, dmodel = inference.make_decoder(config, params,
                                         max_decode_len=8)
    prompt = jnp.asarray([[5, 6]], jnp.int32)
    out, cache = run(prompt, 6, jax.random.PRNGKey(0))
    assert out.shape == (1, 8)
    # Cache index advanced exactly prompt+generated-1 writes... every
    # step writes once: prompt (2) + decode steps (5) = 7? The last
    # sampled token is never fed back. index == total forward calls.
    leaf = jax.tree_util.tree_leaves(
        {k: v for k, v in cache.items()})[0]
    assert leaf is not None

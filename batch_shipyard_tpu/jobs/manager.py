"""Job/task submission and monitoring.

Reference analog: convoy/batch.py add_jobs(:5056 — the 850-line loop) +
_construct_task(:4489) + _add_task_collection(:4313). Our submission
writes task entities + queue messages instead of Batch REST calls; the
node agents do the rest.

Task id generation follows the reference convention (task-%05d,
batch.py:4177) so depends_on_range works identically.
"""

from __future__ import annotations

import json
import re
import time
from typing import Iterator, Optional

from batch_shipyard_tpu.config import settings as settings_mod
from batch_shipyard_tpu.config.settings import (
    JobSettings, PoolSettings, TaskSettings)
from batch_shipyard_tpu.jobs.task_factory import expand_task_factory
from batch_shipyard_tpu.state import names
from batch_shipyard_tpu.state.base import (
    EntityExistsError, EtagMismatchError, NotFoundError, StateStore)
from batch_shipyard_tpu.trace import context as trace_ctx
from batch_shipyard_tpu.trace import spans as trace_spans
from batch_shipyard_tpu.utils import util

logger = util.get_logger(__name__)


class JobExistsError(RuntimeError):
    pass


class JobNotFoundError(RuntimeError):
    pass


def _task_spec(task: TaskSettings, job: JobSettings,
               pool: PoolSettings) -> dict:
    """Serializable task spec stored in the task entity and consumed by
    the node agent (the TaskAddParameter analog)."""
    spec = {
        "command": task.command,
        "runtime": task.runtime,
        "image": task.image,
        "environment_variables": dict(task.environment_variables),
        "tpu": task.tpu,
        "gpus": task.gpus,
        "depends_on": list(task.depends_on),
        "depends_on_range": (list(task.depends_on_range)
                             if task.depends_on_range else None),
        "max_task_retries": task.max_task_retries,
        "max_wall_time_seconds": task.max_wall_time_seconds,
        "progress_deadline_seconds": task.progress_deadline_seconds,
        "retention_time_seconds": task.retention_time_seconds,
        "remove_container_after_exit": task.remove_container_after_exit,
        "shm_size": task.shm_size,
        "container_runtime": (pool.container_runtime_default
                              if pool is not None else "runc"),
        "additional_docker_run_options": list(
            task.additional_docker_run_options),
        "additional_singularity_options": list(
            task.additional_singularity_options),
        "input_data": list(task.input_data),
        "output_data": list(task.output_data),
        "resource_files": list(task.resource_files),
        "environment_variables_secret_id":
            job.environment_variables_secret_id,
        "allow_run_on_missing_image": job.allow_run_on_missing_image,
        "job_preparation_command": job.job_preparation_command,
        "job_input_data": list(job.input_data),
        "auto_scratch": job.auto_scratch,
        "exit_options": dict(task.default_exit_options),
        # Numeric priority: selects the queue band by sign (hi/lo
        # drain order, and retry requeues must land back on the same
        # band) and orders tasks WITHIN the band for the preempt
        # sweep — a pending task with a strictly higher number can
        # evict lower-priority running work.
        "priority": task.priority,
    }
    if task.multi_instance is not None:
        mi = task.multi_instance
        spec["multi_instance"] = {
            "num_instances": mi.resolve_num_instances(pool),
            "min_instances": mi.min_instances,
            "coordination_command": mi.coordination_command,
            "resource_files": list(mi.resource_files),
            "jax_distributed": {
                "enabled": mi.jax_distributed.enabled,
                "coordinator_port": mi.jax_distributed.coordinator_port,
                "transport": mi.jax_distributed.transport,
                "heartbeat_timeout_seconds":
                    mi.jax_distributed.heartbeat_timeout_seconds,
            },
            "pytorch_xla": {"enabled": mi.pytorch_xla},
        }
    return spec


def _expand_job_tasks(store: StateStore, job: JobSettings,
                      pool: PoolSettings,
                      required_node: Optional[str] = None,
                      start_number: int = 0,
                      ) -> list[tuple[str, dict]]:
    """Expand a job's task factories into (task_id, spec) pairs.
    Generic ids are numbered task-%05d from ``start_number``
    (reference id convention, batch.py:4177)."""
    task_number = start_number
    all_task_ids: list[str] = []
    pending: list[tuple[str, dict]] = []
    for raw_task in job.tasks:
        for expanded in expand_task_factory(raw_task, store):
            task = settings_mod.task_settings(expanded, job, pool)
            task_id = task.id or f"task-{task_number:05d}"
            task_number += 1
            spec = _task_spec(task, job, pool)
            if required_node:
                spec["required_node"] = required_node
            pending.append((task_id, spec))
            all_task_ids.append(task_id)
    if job.merge_task is not None:
        # Merge task: runs after every other task of the job
        # (reference batch.py merge_task handling :4177-4242).
        merge_raw = dict(job.merge_task)
        merge_raw["depends_on"] = all_task_ids
        task = settings_mod.task_settings(merge_raw, job, pool)
        merge_id = task.id or "merge-task"
        spec = _task_spec(task, job, pool)
        if required_node:
            spec["required_node"] = required_node
        pending.append((merge_id, spec))
    return pending


def add_jobs(store: StateStore, pool: PoolSettings,
             jobs: list[JobSettings],
             pool_id_override: Optional[str] = None,
             required_node: Optional[str] = None) -> dict[str, int]:
    """Submit jobs + tasks; returns {job_id: task_count}.

    ``required_node`` pins every task to one node (federation
    required-target select): agents bounce non-matching deliveries.
    """
    submitted: dict[str, int] = {}
    for job in jobs:
        pool_id = pool_id_override or job.pool_id or pool.id
        # The distributed trace is born HERE: one trace per job
        # submission, whose root is the submit span. Every task row
        # carries the trace id + its own root span id, so the whole
        # chain (queue wait, claim, rendezvous, program phases) is
        # attributable to this `jobs add`.
        trace = trace_ctx.TraceContext.new()
        submit_started = time.time()
        try:
            store.insert_entity(names.TABLE_JOBS, pool_id, job.id, {
                "state": "active",
                trace_ctx.COL_TRACE_ID: trace.trace_id,
                trace_ctx.COL_TRACE_SPAN: trace.span_id,
                "spec": {
                    "auto_complete": job.auto_complete,
                    "priority": job.priority,
                    "job_release_command": job.job_release_command,
                    "auto_scratch": job.auto_scratch,
                    "recurrence": (
                        {"interval":
                         job.recurrence.recurrence_interval_seconds}
                        if job.recurrence else None),
                },
                "created_at": util.datetime_utcnow_iso(),
            })
        except EntityExistsError:
            raise JobExistsError(f"job {job.id} exists on pool {pool_id}")
        pending = _expand_job_tasks(store, job, pool,
                                    required_node=required_node)
        _submit_tasks_batched(store, pool_id, job.id, pending,
                              priority=job.priority, trace=trace)
        # The submit span covers entity+message fan-out; recorded
        # LAST so its end time is honest. Its own span_id is the
        # trace root (parent of every task's root span).
        trace_spans.emit(
            store, pool_id, trace_spans.SPAN_SUBMIT, trace,
            job_id=job.id, start=submit_started, end=time.time(),
            attrs={"tasks": len(pending)}, self_span=True)
        logger.info("job %s submitted under trace %s", job.id,
                    trace.trace_id)
        submitted[job.id] = len(pending)
    return submitted


_GENERIC_TASK_ID = re.compile(r"^task-(\d{5,})$")


def merge_tasks_into_job(store: StateStore, pool: PoolSettings,
                         job: JobSettings, pool_id: str,
                         required_node: Optional[str] = None) -> int:
    """Add a job spec's tasks to an ALREADY EXISTING job, remapping
    colliding task ids.

    Reference analog: federation schedule_tasks task-id fixup
    (federation/federation.py:2605 fixup + :2699
    regenerate_next_generic_task_id) — a federated action targeting a
    job that already ran on the pool re-numbers generic ids past the
    job's current maximum so the merge never collides; depends_on
    references within the incoming batch are remapped consistently.
    Explicit (non-generic) ids that collide are an error. Returns the
    number of tasks added.
    """
    job_entity = get_job(store, pool_id, job.id)  # must exist
    # Merged tasks join the job's EXISTING trace (their root spans
    # parent under the original submit span); None for legacy jobs.
    trace = trace_ctx.TraceContext.from_entity(job_entity)
    existing = {t["_rk"] for t in list_tasks(store, pool_id, job.id)}
    next_number = 0
    for tid in existing:
        match = _GENERIC_TASK_ID.match(tid)
        if match:
            next_number = max(next_number, int(match.group(1)) + 1)
    # Expand under the batch's OWN numbering (task-00000...), so
    # depends_on references within the incoming batch resolve to
    # batch members; collisions with existing ids are then renumbered
    # past the job's current maximum and the references remapped.
    pending = _expand_job_tasks(store, job, pool,
                                required_node=required_node)
    remap: dict[str, str] = {}
    out: list[tuple[str, dict]] = []
    has_range_deps = any(spec.get("depends_on_range")
                         for _, spec in pending)
    # Renumbered ids must dodge existing ids, ids already assigned in
    # this merge, AND not-yet-processed ids of the incoming batch —
    # otherwise renaming task-00000 to task-00005 collides with an
    # incoming task-00005 later in the same batch.
    taken = set(existing) | {tid for tid, _ in pending}
    for task_id, spec in pending:
        new_id = task_id
        if task_id in existing:
            if has_range_deps:
                # depends_on_range references numeric ids positionally;
                # re-numbering would silently retarget them (the
                # reference likewise skips re-id when dependencies are
                # present, federation.py:2686).
                raise JobExistsError(
                    f"cannot merge tasks into job {job.id}: id "
                    f"{task_id} collides and the batch uses "
                    f"depends_on_range")
            if _GENERIC_TASK_ID.match(task_id) or task_id == "merge-task":
                while f"task-{next_number:05d}" in taken:
                    next_number += 1
                new_id = f"task-{next_number:05d}"
                next_number += 1
            else:
                raise JobExistsError(
                    f"task {task_id} already exists in job {job.id} "
                    f"on pool {pool_id} and is not a generic id")
        taken.add(new_id)
        remap[task_id] = new_id
        out.append((new_id, spec))
    for _, spec in out:
        spec["depends_on"] = [remap.get(d, d)
                              for d in spec.get("depends_on", [])]
    _submit_tasks_batched(store, pool_id, job.id, out,
                          priority=job.priority, trace=trace)
    return len(out)


_SUBMIT_CHUNK = 100


def pool_queue_shards(store: StateStore, pool_id: str) -> int:
    """Task-queue shard count for a pool, read from its stored spec
    (so cross-pool producers — federation, migrate — route to the
    TARGET pool's sharding, not the caller's)."""
    try:
        pool = store.get_entity(names.TABLE_POOLS, "pools", pool_id)
    except NotFoundError:
        return 1
    return int(pool.get("spec", {}).get("pool_specification", {})
               .get("task_queue_shards", 1))


def _submit_tasks_batched(store: StateStore, pool_id: str, job_id: str,
                          tasks: list[tuple[str, dict]],
                          priority: int = 0,
                          trace: Optional[
                              trace_ctx.TraceContext] = None) -> None:
    """Chunked batch submission (the reference's 100-task
    TaskAddCollection chunks, batch.py:4313): one entity batch + one
    message batch per shard per chunk instead of 2N store round
    trips, with messages fanned out over the pool's queue shards.
    ``priority`` selects the queue band agents drain first. ``trace``
    is the submission's context: each task row is stamped with the
    trace id plus its own root span (child of the submit span), and
    queue messages carry the trace id."""
    pk = names.task_pk(pool_id, job_id)
    shards = pool_queue_shards(store, pool_id)
    submitted_at = util.datetime_utcnow_iso()
    for chunk_start in range(0, len(tasks), _SUBMIT_CHUNK):
        chunk = tasks[chunk_start:chunk_start + _SUBMIT_CHUNK]
        rows = []
        for task_id, spec in chunk:
            entity = {
                "state": "pending", "spec": spec, "retries": 0,
                "submitted_at": submitted_at,
            }
            if trace is not None:
                entity.update(trace.child().entity_columns())
            rows.append((pk, task_id, entity))
        store.insert_entities(names.TABLE_TASKS, rows)
        by_queue: dict[str, list[bytes]] = {}
        for task_id, spec in chunk:
            # Per-task numeric priority routes the band (a task may
            # override its job's priority); the job-level param is
            # the legacy fallback for specs without one.
            queue = names.task_queue_for(
                pool_id, task_id, shards,
                priority=int(spec.get("priority", priority) or 0))
            message = {"job_id": job_id, "task_id": task_id}
            if trace is not None:
                message["trace_id"] = trace.trace_id
            num_instances = (spec.get("multi_instance") or {}).get(
                "num_instances")
            if num_instances:
                by_queue.setdefault(queue, []).extend(
                    json.dumps({**message, "instance": k}).encode()
                    for k in range(num_instances))
            else:
                by_queue.setdefault(queue, []).append(
                    json.dumps(message).encode())
        for queue, payloads in by_queue.items():
            store.put_messages(queue, payloads)


def _submit_task(store: StateStore, pool_id: str, job_id: str,
                 task_id: str, spec: dict) -> None:
    _submit_tasks_batched(store, pool_id, job_id, [(task_id, spec)])


def list_jobs(store: StateStore, pool_id: str) -> list[dict]:
    return list(store.query_entities(names.TABLE_JOBS,
                                     partition_key=pool_id))


def get_job(store: StateStore, pool_id: str, job_id: str) -> dict:
    try:
        return store.get_entity(names.TABLE_JOBS, pool_id, job_id)
    except NotFoundError:
        raise JobNotFoundError(job_id)


def list_tasks(store: StateStore, pool_id: str,
               job_id: str) -> list[dict]:
    return list(store.query_entities(
        names.TABLE_TASKS, partition_key=names.task_pk(pool_id, job_id)))


def get_task(store: StateStore, pool_id: str, job_id: str,
             task_id: str) -> dict:
    try:
        return store.get_entity(
            names.TABLE_TASKS, names.task_pk(pool_id, job_id), task_id)
    except NotFoundError:
        raise JobNotFoundError(f"{job_id}/{task_id}")


def wait_for_tasks(store: StateStore, pool_id: str, job_id: str,
                   timeout: float = 600.0,
                   poll_interval: float = 0.2) -> list[dict]:
    """Block until all tasks of a job are terminal; returns them."""
    deadline = time.monotonic() + timeout
    while True:
        tasks = list_tasks(store, pool_id, job_id)
        if tasks and all(t.get("state") in
                         names.TERMINAL_TASK_STATES
                         for t in tasks):
            return tasks
        if time.monotonic() > deadline:
            raise TimeoutError(
                f"tasks of {job_id} not terminal after {timeout}s: "
                f"{ {t['_rk']: t.get('state') for t in tasks} }")
        time.sleep(poll_interval)


def get_task_output(store: StateStore, pool_id: str, job_id: str,
                    task_id: str, filename: str = "stdout.txt",
                    instance: Optional[int] = None) -> bytes:
    name = (f"i{instance}/{filename}" if instance is not None
            else filename)
    key = names.task_output_key(pool_id, job_id, task_id, name)
    return store.get_object(key)


def stream_task_output(store: StateStore, pool_id: str, job_id: str,
                       task_id: str, filename: str = "stdout.txt",
                       timeout: float = 600.0,
                       poll_interval: float = 0.5) -> Iterator[bytes]:
    """Poll-follow a task's output until the task is terminal
    (stream_file_and_wait_for_task analog, batch.py:3243)."""
    offset = 0
    deadline = time.monotonic() + timeout
    key = names.task_output_key(pool_id, job_id, task_id, filename)
    while True:
        task = get_task(store, pool_id, job_id, task_id)
        try:
            data = store.get_object(key)
            if len(data) > offset:
                yield data[offset:]
                offset = len(data)
        except NotFoundError:
            pass
        if task.get("state") in names.TERMINAL_TASK_STATES:
            return
        if time.monotonic() > deadline:
            raise TimeoutError(f"stream of {task_id} timed out")
        time.sleep(poll_interval)


def terminate_job(store: StateStore, pool_id: str, job_id: str,
                  wait: bool = False) -> None:
    """Terminate: mark job + non-terminal tasks; fan out job-release
    (jobs term analog, batch.py:2770 terminate_tasks +
    del_or_term_jobs)."""
    job = get_job(store, pool_id, job_id)
    store.merge_entity(names.TABLE_JOBS, pool_id, job_id,
                       {"state": "terminated",
                        "completed_at": util.datetime_utcnow_iso()})
    pk = names.task_pk(pool_id, job_id)
    for task in list_tasks(store, pool_id, job_id):
        if task.get("state") not in names.TERMINAL_TASK_STATES:
            try:
                store.merge_entity(
                    names.TABLE_TASKS, pk, task["_rk"],
                    {"state": "failed", "exit_code": -9,
                     "error": "job terminated"},
                    if_match=task["_etag"])
            except Exception:
                pass
    for row in store.query_entities(names.TABLE_JOBPREP,
                                    partition_key=pk):
        store.put_message(
            names.control_queue(pool_id, row["_rk"]),
            json.dumps({"type": "job_release",
                        "job_id": job_id}).encode())


def disable_job(store: StateStore, pool_id: str, job_id: str) -> None:
    """Disable: pending tasks stay queued but agents will not start
    them until re-enabled (jobs disable --requeue analog,
    batch.py:2102). Only active jobs can be disabled — a terminated/
    completed job must not be resurrectable via disable+enable."""
    job = get_job(store, pool_id, job_id)
    if job.get("state") != "active":
        raise ValueError(
            f"job {job_id} is {job.get('state')}; only active jobs "
            f"can be disabled")
    store.merge_entity(names.TABLE_JOBS, pool_id, job_id,
                       {"state": "disabled"}, if_match=job["_etag"])


def enable_job(store: StateStore, pool_id: str, job_id: str) -> None:
    job = get_job(store, pool_id, job_id)
    if job.get("state") != "disabled":
        raise ValueError(f"job {job_id} is not disabled")
    store.merge_entity(names.TABLE_JOBS, pool_id, job_id,
                       {"state": "active"})


def migrate_job(store: StateStore, src_pool_id: str, job_id: str,
                dst_pool_id: str) -> int:
    """Live job migration between pools: move the job entity and
    re-enqueue all non-terminal tasks on the destination pool's queue
    (jobs migrate analog, batch.py:1855 check_pool_for_job_migration +
    :1911 update_job_with_pool). Returns moved task count."""
    job = get_job(store, src_pool_id, job_id)
    try:
        get_job(store, dst_pool_id, job_id)
        raise JobExistsError(
            f"job {job_id} already exists on pool {dst_pool_id}")
    except JobNotFoundError:
        pass
    try:
        store.get_entity(names.TABLE_POOLS, "pools", dst_pool_id)
    except NotFoundError:
        raise ValueError(
            f"destination pool {dst_pool_id} does not exist")
    src_pk = names.task_pk(src_pool_id, job_id)
    dst_pk = names.task_pk(dst_pool_id, job_id)
    # Validate BEFORE any mutation: a half-migrated job is
    # unrecoverable without manual store surgery. Requiring the job to
    # be disabled (not merely no-running-tasks) closes the race where
    # a source-pool agent claims a pending task mid-migration.
    if job.get("state") == "active":
        raise RuntimeError(
            f"job {job_id} is active; run jobs disable first, wait "
            f"for running tasks to drain, then migrate")
    tasks = list(store.query_entities(names.TABLE_TASKS,
                                      partition_key=src_pk))
    running = [t["_rk"] for t in tasks
               if t.get("state") in ("assigned", "running")]
    if running:
        raise RuntimeError(
            f"tasks {running} are still running; wait for them to "
            f"drain before migrating")
    moved = 0
    store.insert_entity(names.TABLE_JOBS, dst_pool_id, job_id, {
        "state": job.get("state", "active"), "spec": job.get("spec", {}),
        "created_at": job.get("created_at"),
        "migrated_from": src_pool_id,
    })
    dst_shards = pool_queue_shards(store, dst_pool_id)
    job_priority = int(job.get("spec", {}).get("priority", 0) or 0)
    for task in tasks:
        entity = {k: v for k, v in task.items()
                  if not k.startswith("_")}
        store.insert_entity(names.TABLE_TASKS, dst_pk, task["_rk"],
                            entity)
        store.delete_entity(names.TABLE_TASKS, src_pk, task["_rk"])
        if entity.get("state") in names.CLAIMABLE_TASK_STATES:
            # Per-task priority routes the band, same rule as
            # submission — a hi-band task must not lose its drain
            # precedence by migrating.
            dst_queue = names.task_queue_for(
                dst_pool_id, task["_rk"], dst_shards,
                priority=int((entity.get("spec") or {}).get(
                    "priority", job_priority) or 0))
            message = {"job_id": job_id, "task_id": task["_rk"]}
            if entity.get(trace_ctx.COL_TRACE_ID):
                message["trace_id"] = entity[trace_ctx.COL_TRACE_ID]
            num_instances = (entity.get("spec", {}).get(
                "multi_instance") or {}).get("num_instances")
            if num_instances:
                # Elastic override: a resized gang migrates at its
                # CURRENT effective size — fanning out the spec size
                # onto the destination would wedge the rendezvous the
                # same way it would have on the source.
                effective = int(
                    entity.get(names.TASK_COL_GANG_SIZE)
                    or num_instances)
                for k in range(effective):
                    store.put_message(
                        dst_queue,
                        json.dumps({**message,
                                    "instance": k}).encode())
            else:
                store.put_message(
                    dst_queue, json.dumps(message).encode())
            moved += 1
        if (entity.get("spec", {}).get("multi_instance")
                or {}).get("num_instances"):
            # Source-pool rendezvous rows would otherwise orphan:
            # gang partitions are POOL-scoped, so the destination's
            # janitor can never sweep them, and the source pool may
            # have no live agents left to (the migration trigger).
            attempts = (int(entity.get("retries", 0) or 0)
                        + int(entity.get(
                            names.TASK_COL_PREEMPT_COUNT, 0) or 0)
                        + int(entity.get(
                            names.TASK_COL_EVICT_COUNT, 0) or 0))
            for attempt in range(attempts + 1):
                gang_pk = names.gang_pk(src_pool_id, job_id,
                                        task["_rk"], attempt=attempt)
                for gang_row in list(store.query_entities(
                        names.TABLE_GANGS, partition_key=gang_pk)):
                    try:
                        store.delete_entity(names.TABLE_GANGS,
                                            gang_pk,
                                            gang_row["_rk"])
                    except NotFoundError:
                        pass
    store.delete_entity(names.TABLE_JOBS, src_pool_id, job_id)
    return moved


def cleanup_mi_containers(store: StateStore, pool_id: str) -> int:
    """Fan out orphaned multi-instance container cleanup to every node
    (jobs cmi analog, batch.py:2322). Returns node count."""
    count = 0
    for node in store.query_entities(names.TABLE_NODES,
                                     partition_key=pool_id):
        store.put_message(
            names.control_queue(pool_id, node["_rk"]),
            json.dumps({"type": "cleanup_mi"}).encode())
        count += 1
    return count


def terminate_task(store: StateStore, pool_id: str, job_id: str,
                   task_id: str, wait: bool = False,
                   timeout: float = 60.0) -> None:
    """Terminate one task (tasks term analog, batch.py:2770): pending
    tasks are marked failed; running tasks get a kill relayed to their
    node's agent."""
    task = get_task(store, pool_id, job_id, task_id)
    state = task.get("state")
    if state in names.TERMINAL_TASK_STATES:
        return
    if state in names.CLAIMABLE_TASK_STATES:
        # pending OR preempted-awaiting-reclaim: nothing is running,
        # mark terminal directly.
        try:
            store.merge_entity(
                names.TABLE_TASKS, names.task_pk(pool_id, job_id),
                task_id, {"state": "failed", "exit_code": -9,
                          "error": "terminated by user"},
                if_match=task["_etag"])
            return
        except EtagMismatchError:
            task = get_task(store, pool_id, job_id, task_id)
    node_id = task.get("node_id")
    if node_id:
        store.put_message(
            names.control_queue(pool_id, node_id),
            json.dumps({"type": "term_task", "job_id": job_id,
                        "task_id": task_id}).encode())
    if wait:
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            task = get_task(store, pool_id, job_id, task_id)
            if task.get("state") in names.TERMINAL_TASK_STATES:
                return
            time.sleep(0.2)
        raise TimeoutError(f"task {task_id} did not terminate")


def request_preemption(store: StateStore, pool_id: str, job_id: str,
                       task_id: str, reason: str = "",
                       by_job_id: Optional[str] = None,
                       by_task_id: Optional[str] = None,
                       leader_epoch: Optional[int] = None,
                       defer_notice: bool = False):
    """Stamp a cooperative preempt request on a RUNNING task. The
    owning node's agent heartbeat loop delivers it into the live task
    dirs (every gang instance gets its copy); an instrumented workload
    drains to its next step boundary, forces a COMMITTED checkpoint,
    and exits EXIT_PREEMPTED — requeued at full retry budget. Returns
    False when the task is not in a preemptible state (or a concurrent
    transition won the merge). Idempotent: re-stamping an already
    pending request is a no-op (one drain per request).

    ``leader_epoch`` is the preempt-sweep term's fencing epoch
    (state/leases.py): stamped into the request and the notice event
    so every stamp is attributable to exactly one leadership term —
    the partition drill's zero-double-fire invariant reads it.
    Manual CLI preemptions carry None (no term to fence).

    ``defer_notice``: return the notice-emitting closure (truthy)
    instead of publishing the TASK_PREEMPT_NOTICE event here — for
    the leader sweep, whose post-write fence check may RETRACT a
    stamp that landed after its term ended; emitting eagerly would
    leave a dangling notice event for a preemption that never
    happened. The caller invokes the closure once the stamp is known
    to stand."""
    from batch_shipyard_tpu.goodput import events as goodput_events
    task = get_task(store, pool_id, job_id, task_id)
    if task.get("state") not in ("assigned", "running"):
        return False
    if task.get(names.TASK_COL_PREEMPT_REQUEST):
        return True  # already pending; one request, one drain
    request = {
        "requested_at": util.datetime_utcnow_iso(),
        "reason": reason or "preempted by scheduler",
        "by_job_id": by_job_id, "by_task_id": by_task_id,
        "leader_epoch": leader_epoch,
    }
    try:
        store.merge_entity(
            names.TABLE_TASKS, names.task_pk(pool_id, job_id),
            task_id, {names.TASK_COL_PREEMPT_REQUEST: request},
            if_match=task["_etag"])
    except (EtagMismatchError, NotFoundError):
        return False

    def _emit_notice() -> None:
        goodput_events.emit(
            store, pool_id, goodput_events.TASK_PREEMPT_NOTICE,
            job_id=job_id, task_id=task_id,
            attrs={"reason": request["reason"],
                   "by_job_id": by_job_id, "by_task_id": by_task_id,
                   "leader_epoch": leader_epoch},
            trace_id=task.get(trace_ctx.COL_TRACE_ID),
            span_id=task.get(trace_ctx.COL_TRACE_SPAN))
        logger.warning("preempt requested for %s/%s: %s", job_id,
                       task_id, request["reason"])

    if defer_notice:
        return _emit_notice
    _emit_notice()
    return True


def list_task_files(store: StateStore, pool_id: str, job_id: str,
                    task_id: str) -> list[str]:
    """List a task's uploaded files (data files list analog)."""
    prefix = names.task_output_key(pool_id, job_id, task_id, "")
    return [k[len(prefix):] for k in store.list_objects(prefix)]


def delete_task(store: StateStore, pool_id: str, job_id: str,
                task_id: str, require_terminal: bool = True) -> None:
    """Delete a task's entity and its uploaded objects (tasks del
    analog). Non-terminal tasks must be terminated first."""
    task = get_task(store, pool_id, job_id, task_id)
    if require_terminal and task.get("state") not in \
            names.TERMINAL_TASK_STATES:
        raise ValueError(
            f"task {task_id} is {task.get('state')}; terminate first")
    prefix = names.task_output_key(pool_id, job_id, task_id, "")
    for key in store.list_objects(prefix):
        store.delete_object(key)
    store.delete_entity(names.TABLE_TASKS,
                        names.task_pk(pool_id, job_id), task_id)


def delete_job(store: StateStore, pool_id: str, job_id: str) -> None:
    get_job(store, pool_id, job_id)
    pk = names.task_pk(pool_id, job_id)
    for task in list(store.query_entities(names.TABLE_TASKS,
                                          partition_key=pk)):
        delete_task(store, pool_id, job_id, task["_rk"],
                    require_terminal=False)
    for row in list(store.query_entities(names.TABLE_JOBPREP,
                                         partition_key=pk)):
        store.delete_entity(names.TABLE_JOBPREP, pk, row["_rk"])
    store.delete_entity(names.TABLE_JOBS, pool_id, job_id)


def job_stats(store: StateStore, pool_id: str,
              job_id: Optional[str] = None) -> dict:
    """jobs stats analog (batch.py:1972), plus queue/run aggregates
    sourced from the goodput event log: queue_seconds sums queued
    spans (submit->first claim; requeue->re-claim for retries, one
    span per gang regardless of width), run_seconds sums running
    spans (node-seconds: gang tasks contribute one span per
    instance)."""
    from batch_shipyard_tpu.goodput import events as goodput_events
    jobs = ([get_job(store, pool_id, job_id)] if job_id
            else list_jobs(store, pool_id))
    stats = {"jobs": len(jobs), "tasks": 0, "by_state": {},
             "wall_seconds_total": 0.0,
             "queue_seconds": 0.0, "run_seconds": 0.0}
    job_ids = {job["_rk"] for job in jobs}
    for job in jobs:
        for task in list_tasks(store, pool_id, job["_rk"]):
            stats["tasks"] += 1
            state = task.get("state", "pending")
            stats["by_state"][state] = stats["by_state"].get(state, 0) + 1
            stats["wall_seconds_total"] += float(
                task.get("wall_seconds", 0.0) or 0.0)
    # One unsorted pass over the pool's event partition (no need for
    # events.query's time ordering here; the log is bounded by
    # `goodput prune` retention).
    for event in store.query_entities(names.TABLE_GOODPUT,
                                      partition_key=pool_id):
        if event.get("job_id") not in job_ids or \
                event.get("kind") not in (goodput_events.TASK_QUEUED,
                                          goodput_events.TASK_RUNNING):
            continue
        duration = max(0.0, float(event.get("end", 0.0))
                       - float(event.get("start", 0.0)))
        if event.get("kind") == goodput_events.TASK_QUEUED:
            stats["queue_seconds"] += duration
        else:
            stats["run_seconds"] += duration
    return stats

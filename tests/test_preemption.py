"""Cooperative preemption + elastic gang resize (ROADMAP item 3).

Covers the full control plane: numeric priority victim election by
the leader sweep, heartbeat-path request delivery, the drain ->
forced-COMMITTED-checkpoint -> EXIT_PREEMPTED contract
(workloads/preempt_probe.py speaks it without importing jax), the
full-budget/neutral-health requeue, the preemption_recovery goodput
leg, and elastic gangs re-forming at surviving size. All CPU fakepod.
"""

import os
import pathlib
import signal
import sys
import time

import pytest

from batch_shipyard_tpu.agent import preemption
from batch_shipyard_tpu.config import settings as settings_mod
from batch_shipyard_tpu.goodput import accounting
from batch_shipyard_tpu.goodput import events as goodput_events
from batch_shipyard_tpu.jobs import manager as jobs_mgr
from batch_shipyard_tpu.pool import manager as pool_mgr
from batch_shipyard_tpu.state import names

REPO_ROOT = str(pathlib.Path(__file__).resolve().parent.parent)

PROBE = (f"{sys.executable} -m "
         f"batch_shipyard_tpu.workloads.preempt_probe")


def _make_pool(pool_id, accelerator=None, nodes=2, slots=1,
               **agent_kwargs):
    from batch_shipyard_tpu.state.memory import MemoryStateStore
    from batch_shipyard_tpu.substrate.fakepod import FakePodSubstrate
    store = MemoryStateStore()
    substrate = FakePodSubstrate(store, heartbeat_interval=0.2,
                                 node_stale_seconds=2.0)
    substrate.agent_kwargs = {
        "claim_visibility_seconds": 3.0, "gang_sweep_interval": 1.0,
        "retry_backoff_base": 0.2, "retry_backoff_cap": 1.0,
        **agent_kwargs}
    spec = {"id": pool_id, "substrate": "fake",
            "task_slots_per_node": slots,
            "max_wait_time_seconds": 30}
    if accelerator:
        spec["tpu"] = {"accelerator_type": accelerator}
    else:
        spec["vm_configuration"] = {"vm_count": {"dedicated": nodes}}
    conf = {"pool_specification": spec}
    pool = settings_mod.pool_settings(conf)
    pool_mgr.create_pool(store, substrate, pool,
                         settings_mod.global_settings({}), conf)
    return store, substrate, pool


def _wait_running(store, pool_id, job_id, task_id, timeout=25):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        task = jobs_mgr.get_task(store, pool_id, job_id, task_id)
        if task.get("state") == "running":
            return task
        time.sleep(0.1)
    raise AssertionError(f"{task_id} never reached running: {task}")


def test_preempt_watcher_contract(tmp_path):
    """write_request is atomic, read round-trips, poll latches once
    (a loop polling mid-drain must not trigger a second drain), and
    with no env/path the watcher is a disarmed no-op."""
    path = str(tmp_path / "req.json")
    assert preemption.PreemptWatcher(path).poll() is None
    preemption.write_request(path, reason="test", extra_key=1)
    request = preemption.read_request(path)
    assert request["reason"] == "test"
    assert request["extra_key"] == 1
    assert request["requested_at"]
    watcher = preemption.PreemptWatcher(path)
    assert watcher.armed
    first = watcher.poll()
    assert first and first["reason"] == "test"
    assert watcher.poll() is None  # latched
    assert not watcher.armed
    # No sink configured: disarmed (the out-of-pool no-op rule).
    assert os.environ.get(preemption.PREEMPT_REQUEST_FILE_ENV) is None
    disarmed = preemption.PreemptWatcher()
    assert not disarmed.armed
    assert disarmed.poll() is None


def test_request_preemption_requires_running(mem_statestore):
    """Only assigned/running tasks are preemptible; stamping is
    idempotent (one pending request -> one drain)."""
    store = mem_statestore
    pk = names.task_pk("p", "j")
    store.insert_entity(names.TABLE_TASKS, pk, "t",
                        {"state": "pending", "spec": {}})
    assert not jobs_mgr.request_preemption(store, "p", "j", "t")
    store.merge_entity(names.TABLE_TASKS, pk, "t",
                       {"state": "running"})
    assert jobs_mgr.request_preemption(store, "p", "j", "t",
                                       reason="r1")
    stamped = store.get_entity(names.TABLE_TASKS, pk, "t")
    request = stamped[names.TASK_COL_PREEMPT_REQUEST]
    assert request["reason"] == "r1"
    # Idempotent: the pending request is not overwritten (its
    # requested_at is the delivery dedup key).
    assert jobs_mgr.request_preemption(store, "p", "j", "t",
                                       reason="r2")
    again = store.get_entity(names.TABLE_TASKS, pk, "t")
    assert again[names.TASK_COL_PREEMPT_REQUEST] == request
    # The notice marker landed in the goodput log.
    kinds = [e["kind"] for e in goodput_events.query(store, "p")]
    assert kinds.count(goodput_events.TASK_PREEMPT_NOTICE) == 1


def test_regular_task_preempted_resumes_at_full_budget(tmp_path):
    """Acceptance e2e (regular task): preempt request -> heartbeat
    delivery -> drain -> forced COMMITTED checkpoint -> distinct
    preempted exit -> requeue with retries UNTOUCHED and node health
    UNDEBITED -> resume from the barrier with zero lost steps ->
    preemption_recovery priced, partition exact."""
    store, substrate, pool = _make_pool("pp", nodes=1)
    ckpt = str(tmp_path / "state.json")
    try:
        jobs = settings_mod.job_settings_list({"job_specifications": [{
            "id": "j1",
            "tasks": [{"id": "t0",
                       "command": (f"{PROBE} --steps 40 "
                                   f"--step-seconds 0.05 "
                                   f"--ckpt {ckpt}"),
                       "environment_variables": {
                           "PYTHONPATH": REPO_ROOT},
                       "max_task_retries": 2}],
        }]})
        jobs_mgr.add_jobs(store, pool, jobs)
        _wait_running(store, "pp", "j1", "t0")
        time.sleep(0.4)
        assert jobs_mgr.request_preemption(store, "pp", "j1", "t0",
                                           reason="test")
        rows = jobs_mgr.wait_for_tasks(store, "pp", "j1", timeout=60,
                                       poll_interval=0.2)
        task = rows[0]
        assert task["state"] == "completed"
        assert task.get("retries", 0) == 0
        assert task.get(names.TASK_COL_PREEMPT_COUNT) == 1
        # Ledger: barrier-contiguous, no replay, no gap.
        ledger = [line.split() for line in open(
            ckpt + ".steps.log", encoding="utf-8")]
        assert ledger[0][2] == "preempted"
        assert ledger[-1][2] == "completed"
        cursor = 0
        for _inst, span, _status in ledger:
            lo, hi = span.split("..")
            assert int(lo) == cursor, ledger
            cursor = int(hi)
        assert cursor == 40
        # Health untouched: a preempted exit is neutral.
        for node in store.query_entities(names.TABLE_NODES,
                                         partition_key="pp"):
            assert float(node.get(names.NODE_COL_HEALTH, 1.0)) >= 1.0
            assert not node.get(names.NODE_COL_QUARANTINED)
        report = accounting.pool_report(store, "pp",
                                        include_jobs=False)
        assert report["badput_seconds"]["preemption_recovery"] > 0
        total = (report["productive_seconds"]
                 + sum(report["badput_seconds"].values())
                 + sum(report["overlapped_seconds"].values()))
        assert abs(total - report["wall_seconds"]) <= max(
            1e-6 * max(1.0, report["wall_seconds"]), 1e-6)
    finally:
        substrate.stop_all()


def test_spurious_preempt_exit_is_budgeted():
    """EXIT_PREEMPTED without a pending preempt request is NOT a
    preemption: the retry supervisor prices it (otherwise a buggy
    always-75 task requeues at full budget forever)."""
    store, substrate, pool = _make_pool("sp", nodes=1)
    try:
        jobs = settings_mod.job_settings_list({"job_specifications": [{
            "id": "js",
            "tasks": [{"id": "t0", "runtime": "inproc",
                       "command": "preempt-exit",
                       "max_task_retries": 1}],
        }]})
        jobs_mgr.add_jobs(store, pool, jobs)
        rows = jobs_mgr.wait_for_tasks(store, "sp", "js", timeout=40,
                                       poll_interval=0.2)
        task = rows[0]
        # Budget (1) burned, then quarantined — never a full-budget
        # preempt loop.
        assert task["state"] == names.TASK_STATE_QUARANTINED
        assert task.get("retries") == 1
        assert not task.get(names.TASK_COL_PREEMPT_COUNT)
    finally:
        substrate.stop_all()


def test_preempt_sweep_elects_lower_priority_victim(tmp_path):
    """Numeric priority within a band: a pending priority-5 task that
    cannot place (single slot held by priority-0 work) is starved
    past the grace window; the leader sweep elects the running task
    as victim, it drains cooperatively, and the high-priority task
    runs in the freed slot. The victim then resumes and completes —
    at full retry budget."""
    store, substrate, pool = _make_pool(
        "sw", nodes=1, preempt_sweep_interval=0.5,
        preempt_grace_seconds=0.3)
    ckpt = str(tmp_path / "state.json")
    try:
        jobs = settings_mod.job_settings_list({"job_specifications": [{
            "id": "lo",
            "tasks": [{"id": "victim",
                       "command": (f"{PROBE} --steps 50 "
                                   f"--step-seconds 0.05 "
                                   f"--ckpt {ckpt}"),
                       "environment_variables": {
                           "PYTHONPATH": REPO_ROOT},
                       "max_task_retries": 2}],
        }]})
        jobs_mgr.add_jobs(store, pool, jobs)
        _wait_running(store, "sw", "lo", "victim")
        hi = settings_mod.job_settings_list({"job_specifications": [{
            "id": "hi",
            "tasks": [{"id": "urgent", "runtime": "inproc",
                       "command": "noop", "priority": 5}],
        }]})
        jobs_mgr.add_jobs(store, pool, hi)
        hi_rows = jobs_mgr.wait_for_tasks(store, "sw", "hi",
                                          timeout=40,
                                          poll_interval=0.2)
        assert hi_rows[0]["state"] == "completed"
        lo_rows = jobs_mgr.wait_for_tasks(store, "sw", "lo",
                                          timeout=60,
                                          poll_interval=0.2)
        victim = lo_rows[0]
        assert victim["state"] == "completed"
        assert victim.get("retries", 0) == 0
        assert victim.get(names.TASK_COL_PREEMPT_COUNT, 0) >= 1
        # The sweep's notice named the starved task.
        notices = [e for e in goodput_events.query(store, "sw")
                   if e["kind"] == goodput_events.TASK_PREEMPT_NOTICE]
        assert notices and \
            notices[0]["attrs"]["by_task_id"] == "urgent"
    finally:
        substrate.stop_all()


def test_gang_preempted_as_unit_resumes_from_barrier(tmp_path):
    """A preempt request on a gang task reaches EVERY instance (each
    node's heartbeat delivers into its own instance dir); the gang
    drains as a unit, finalizes with the preempted status, requeues
    ALL instances at full budget, and the rerun resumes from the
    forced commit."""
    store, substrate, pool = _make_pool("gp",
                                        accelerator="v5litepod-16")
    ckpt = os.path.join(substrate.work_root, "probe", "state.json")
    try:
        jobs = settings_mod.job_settings_list({"job_specifications": [{
            "id": "jg",
            "tasks": [{"id": "g0",
                       "command": (f"{PROBE} --steps 40 "
                                   f"--step-seconds 0.05 "
                                   f"--ckpt {ckpt}"),
                       "environment_variables": {
                           "PYTHONPATH": REPO_ROOT},
                       "max_task_retries": 2,
                       "multi_instance": {
                           "num_instances": 2,
                           "jax_distributed": {"enabled": False}}}],
        }]})
        jobs_mgr.add_jobs(store, pool, jobs)
        _wait_running(store, "gp", "jg", "g0")
        time.sleep(0.6)
        assert jobs_mgr.request_preemption(store, "gp", "jg", "g0",
                                           reason="gang test")
        rows = jobs_mgr.wait_for_tasks(store, "gp", "jg", timeout=60,
                                       poll_interval=0.2)
        task = rows[0]
        assert task["state"] == "completed"
        assert task.get("retries", 0) == 0
        assert task.get(names.TASK_COL_PREEMPT_COUNT) == 1
        ledger = [line.split() for line in open(
            ckpt + ".steps.log", encoding="utf-8")]
        assert ledger[0][2] == "preempted"
        assert ledger[-1][2] == "completed"
        assert ledger[1][1].split("..")[0] == \
            ledger[0][1].split("..")[1]
        assert not list(store.query_entities(names.TABLE_GANGS))
    finally:
        substrate.stop_all()


def test_elastic_gang_resizes_to_surviving_nodes():
    """Acceptance e2e: a 4-wide elastic gang (min_instances=2) loses
    2 of its 4 nodes mid-run; recovery re-forms it at size 2 (the
    rerun sees SHIPYARD_TASK_INSTANCES=2), a GANG_RESIZE event is
    emitted, and no gang rows leak."""
    store, substrate, pool = _make_pool("el",
                                        accelerator="v5litepod-16",
                                        gang_timeout=10.0)
    try:
        jobs = settings_mod.job_settings_list({"job_specifications": [{
            "id": "je",
            "tasks": [{"id": "g0",
                       "command": ("sleep 2.5 && echo elastic-"
                                   "$SHIPYARD_TASK_INSTANCES"),
                       "max_task_retries": 3,
                       "multi_instance": {
                           "num_instances": 4, "min_instances": 2,
                           "jax_distributed": {"enabled": False}}}],
        }]})
        jobs_mgr.add_jobs(store, pool, jobs)
        _wait_running(store, "el", "je", "g0")
        time.sleep(0.5)
        for node_id in ["el-s0-w2", "el-s0-w3"]:
            agent = substrate.agent("el", node_id)
            agent.stop_event.set()
            for proc in list(agent._live_procs.values()):
                try:
                    os.killpg(os.getpgid(proc.pid), signal.SIGKILL)
                except (ProcessLookupError, OSError):
                    pass
            substrate.crash_node("el", node_id)
        rows = jobs_mgr.wait_for_tasks(store, "el", "je", timeout=90,
                                       poll_interval=0.2)
        task = rows[0]
        assert task["state"] == "completed"
        assert task.get(names.TASK_COL_GANG_SIZE) == 2
        out = jobs_mgr.get_task_output(store, "el", "je", "g0",
                                       instance=0)
        assert out.strip() == b"elastic-2"
        resizes = [e for e in goodput_events.query(store, "el")
                   if e["kind"] == goodput_events.GANG_RESIZE]
        assert resizes and resizes[0]["attrs"]["new_size"] == 2
        assert resizes[0]["attrs"]["old_size"] == 4
        assert not list(store.query_entities(names.TABLE_GANGS))
    finally:
        substrate.stop_all()


def test_elastic_gang_resizes_when_formation_starved():
    """A gang that can NEVER form at its spec size (4 instances, 2
    nodes) re-forms at the elastic floor on rendezvous timeout
    instead of failing terminally — the formation-starved resize
    path."""
    store, substrate, pool = _make_pool("ef", nodes=2,
                                        gang_timeout=3.0)
    try:
        jobs = settings_mod.job_settings_list({"job_specifications": [{
            "id": "jf",
            "tasks": [{"id": "g0",
                       "command": ("echo formed-"
                                   "$SHIPYARD_TASK_INSTANCES"),
                       "max_task_retries": 2,
                       "multi_instance": {
                           "num_instances": 4, "min_instances": 2,
                           "jax_distributed": {"enabled": False}}}],
        }]})
        jobs_mgr.add_jobs(store, pool, jobs)
        rows = jobs_mgr.wait_for_tasks(store, "ef", "jf", timeout=60,
                                       poll_interval=0.2)
        task = rows[0]
        assert task["state"] == "completed"
        assert task.get(names.TASK_COL_GANG_SIZE) == 2
        out = jobs_mgr.get_task_output(store, "ef", "jf", "g0",
                                       instance=0)
        assert out.strip() == b"formed-2"
        assert not list(store.query_entities(names.TABLE_GANGS))
    finally:
        substrate.stop_all()


def test_rigid_gang_rendezvous_timeout_still_fails():
    """No min_instances floor = the historical contract: a gang that
    cannot form fails with the rendezvous timeout, never silently
    shrinks."""
    store, substrate, pool = _make_pool("rg", nodes=2,
                                        gang_timeout=2.0)
    try:
        jobs = settings_mod.job_settings_list({"job_specifications": [{
            "id": "jr",
            "tasks": [{"id": "g0", "command": "echo never",
                       "multi_instance": {
                           "num_instances": 4,
                           "jax_distributed": {"enabled": False}}}],
        }]})
        jobs_mgr.add_jobs(store, pool, jobs)
        rows = jobs_mgr.wait_for_tasks(store, "rg", "jr", timeout=40,
                                       poll_interval=0.2)
        assert rows[0]["state"] == "failed"
        assert "rendezvous timeout" in rows[0].get("error", "")
    finally:
        substrate.stop_all()


def test_inproc_runtime_end_to_end():
    """runtime: "inproc" — the 10^5-proof task mode: noop completes,
    fail retries through the supervisor, unknown commands exit 127;
    no task dir or output files are created (the whole point)."""
    store, substrate, pool = _make_pool("ip", nodes=1, slots=2)
    try:
        jobs = settings_mod.job_settings_list({"job_specifications": [{
            "id": "ji",
            "tasks": [
                {"id": "ok", "runtime": "inproc", "command": "noop"},
                {"id": "bad", "runtime": "inproc",
                 "command": "does-not-exist"},
            ],
        }]})
        jobs_mgr.add_jobs(store, pool, jobs)
        rows = {t["_rk"]: t for t in jobs_mgr.wait_for_tasks(
            store, "ip", "ji", timeout=30, poll_interval=0.1)}
        assert rows["ok"]["state"] == "completed"
        assert rows["bad"]["state"] == "failed"
        assert rows["bad"]["exit_code"] == 127
        # No files: the runner never touched the task dir.
        agent = substrate.agent("ip", "ip-s0-w0")
        task_dir = os.path.join(agent.work_dir, "tasks", "ji", "ok")
        assert not os.path.exists(
            os.path.join(task_dir, "stdout.txt"))
    finally:
        substrate.stop_all()


def test_scheduler_scale_smoke():
    """The scheduler_scale bench phase end-to-end at a tier-1-sized
    count (10^4): every task completes through the real scheduling
    path — server-side expansion, streaming batched submission,
    batched claims, summary-based drain — throughput is reported, and
    the goodput partition is exact. (The committed
    BENCH_scheduler_scale.json artifact is the 10^6 run of exactly
    this code.)"""
    sys.path.insert(0, REPO_ROOT)
    import bench
    result = bench.bench_scheduler_scale(
        num_tasks=10_000, nodes=2, slots=2, shards=2, timeout=240,
        artifact=False)
    assert result["completed"], result
    assert result["by_state"] == {"completed": 10_000}
    assert result["goodput"]["partition_exact"], result
    assert result["tasks_per_second"] > 0
    assert result["queue_depth_after"] == 0
    # The submit leg is materialized pool-side (one expansion row
    # from the client) and its breakdown is priced.
    assert result["server_side_expansion"] is True
    breakdown = result["submit_breakdown"]
    assert breakdown["messages"] == 10_000
    assert breakdown["expansion_wall_seconds"] > 0
    assert result["submit_seconds"] < result["run_seconds"]


@pytest.mark.slow
def test_scheduler_scale_million():
    """The full 10^6-task artifact run (slow phase): the committed
    BENCH_scheduler_scale.json is regenerated by exactly this call
    via `python bench.py --workloads scheduler_scale`."""
    sys.path.insert(0, REPO_ROOT)
    import bench
    result = bench.bench_scheduler_scale(artifact=False)
    assert result["num_tasks"] == 1_000_000
    assert result["completed"], result
    assert result["goodput"]["partition_exact"], result
    assert result["submit_seconds"] < result["run_seconds"]


@pytest.mark.slow
def test_preemption_drill_acceptance():
    """The full seeded preemption drill (chaos drill --preempt): a
    node_preempt_notice schedule against a running gang — all
    invariants asserted inside run_preemption_drill."""
    from batch_shipyard_tpu.chaos import drill
    report = drill.run_preemption_drill(seed=1)
    assert report["invariants"]["ok"]
    assert report["invariants"]["retries"] == 0
    assert report["invariants"]["preempt_count"] >= 1


@pytest.mark.slow
def test_victim_selection_drill_acceptance():
    """ISSUE 17's live victim-cost proof (chaos drill --victim): two
    equal-priority eligible victims where the deterministic
    (priority, task_id) tie-break points at the EXPENSIVE one
    ("aa-costly" sorts before "zz-cheap") — the sweep must elect the
    cheap victim anyway, proving the goodput-cost term from synced
    sched hints decided the election, not scan order or id order.
    All invariants asserted inside run_victim_selection_drill."""
    from batch_shipyard_tpu.chaos import drill
    report = drill.run_victim_selection_drill(seed=0)
    assert report["invariants"]["ok"]
    assert report["invariants"]["retries"] == 0
    assert report["invariants"]["cheap_preempt_count"] >= 1
    assert report["invariants"]["costly_preempt_count"] == 0
    costs = report["invariants"]["victim_costs"]
    assert costs["aa-costly"] > costs["zz-cheap"]

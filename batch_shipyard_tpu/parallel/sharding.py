"""Parameter/activation sharding rules: how models map onto the mesh.

The scaling-book recipe: pick a mesh (parallel/mesh.py), annotate
shardings (this module), let XLA insert the collectives. Rules are
path-pattern based so the model code stays sharding-agnostic.

Transformer (Megatron-style tensor parallel over 'tp', optional fsdp
over 'fsdp'):
  - q/k/v/gate/up projections: columns over tp  -> P(fsdp?, 'tp')
  - o/down projections:        rows over tp     -> P('tp', fsdp?)
  - embedding:                 vocab over tp    -> P('tp', fsdp?)
  - norms/scales: replicated
Activations: batch over (dp, fsdp), sequence over sp.

ResNet: pure data parallel (convs don't tensor-parallelize profitably
at this scale) — all params replicated, batch over every mesh axis.
"""

from __future__ import annotations

import re
from typing import Any

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

_TRANSFORMER_RULES: list[tuple[str, P]] = [
    (r".*(q_proj|k_proj|v_proj|gate_proj|up_proj)/kernel$",
     P("fsdp", "tp")),
    # Fused-norm path (models/transformer.py fused_norm): the merged
    # qkv / gate-up projections are column-sharded like their unfused
    # counterparts.
    (r".*(qkv_kernel|gate_up_kernel)$", P("fsdp", "tp")),
    (r".*(o_proj|down_proj)/kernel$", P("tp", "fsdp")),
    (r".*embed/embedding$", P("tp", "fsdp")),
    # MoE: experts over ep, expert-internal dims over fsdp/tp.
    (r".*moe/router/kernel$", P()),
    (r".*moe/(w_gate|w_up)$", P("ep", "fsdp", "tp")),
    (r".*moe/w_down$", P("ep", "tp", "fsdp")),
    (r".*(scale|bias)$", P()),
]


def _path_str(path) -> str:
    parts = []
    for key in path:
        if hasattr(key, "key"):
            parts.append(str(key.key))
        elif hasattr(key, "idx"):
            parts.append(str(key.idx))
        else:
            parts.append(str(key))
    return "/".join(parts)


def transformer_param_specs(params) -> Any:
    """PartitionSpec pytree for TransformerLM params."""
    def rule(path, leaf):
        path_s = _path_str(path)
        for pattern, spec in _TRANSFORMER_RULES:
            if re.match(pattern, path_s):
                return spec
        return P()
    return jax.tree_util.tree_map_with_path(rule, params)


def replicated_specs(params) -> Any:
    return jax.tree_util.tree_map(lambda _: P(), params)


def to_shardings(mesh: Mesh, spec_tree) -> Any:
    return jax.tree_util.tree_map(
        lambda spec: NamedSharding(mesh, spec), spec_tree,
        is_leaf=lambda s: isinstance(s, P))


def place(mesh: Mesh, tree, spec_tree):
    """Device-put a pytree according to a spec tree."""
    shardings = to_shardings(mesh, spec_tree)
    return jax.device_put(tree, shardings)
